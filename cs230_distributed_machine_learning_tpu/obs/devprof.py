"""Device-time attribution + on-demand deep profiling.

Four rounds of kernel/data-plane work are valve-gated and CPU-verified
while the flagship number sits flat — the missing layer is knowing, on a
LIVE system, where device time actually goes. Two instruments:

- **Per-phase device-seconds**: every executed batch's measured phase
  totals (the trial engine's ``compile`` / ``stage`` / ``dispatch`` /
  ``fetch`` timers, already derived from ``block_until_ready`` deltas
  around each dispatch) accumulate into
  ``tpuml_executor_device_seconds_total{phase=}`` — a *counter*, so the
  embedded time-series ring (obs/timeseries.py) samples it for free and
  ``/dashboard`` can draw a device-seconds-per-second-by-phase rate with
  no new sampling machinery. The executor feeds it for local batches
  (:func:`record_batch_device_seconds`) and the coordinator's
  ``push_metrics`` ingest feeds it for remote agents' batches (same
  ``batch_primary`` + ``obs_pid`` dedup contract as the phase
  histograms — docs/OBSERVABILITY.md).
- **Programmatic ``jax.profiler`` capture**: ``POST /profile/start`` /
  ``POST /profile/stop`` (runtime/server.py) bracket a live workload with
  a real XLA trace dumped under ``<journal_dir>/profile/<tag>`` — the
  deep-inspection path that previously required restarting the
  coordinator with ``execution.enable_profiler``. One capture at a time;
  start/stop land in the flight recorder (``profile.start`` /
  ``profile.stop``) so the capture window is visible next to the
  scheduling decisions it brackets.

Everything is valve-gated by ``CS230_OBS`` like the rest of ``obs/``:
disabled, the recorder helpers return after one env read and profile
capture refuses to start.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from .metrics import REGISTRY
from .recorder import record_event
from .tracing import _enabled, journal_dir

#: the attribution phases, in pipeline order. ``dispatch`` is the device
#: execution window minus the blocking fetches it contains — the four
#: batch phases sum to (compile + stage + run) wall, not double-counting
#: fetch. ``stream`` is the out-of-core overlap phase: the share of a
#: streaming pass's host->device transfer wall HIDDEN behind compute by
#: the double-buffered uploader (data/streaming.py) — the blocking
#: remainder rides the engine's ordinary ``stage`` accumulator, so
#: stage + stream together are the full streamed-transfer wall.
PHASES = ("stage", "compile", "dispatch", "fetch", "stream")

DEVICE_SECONDS = "tpuml_executor_device_seconds_total"


def device_seconds(phase: str, seconds: float) -> None:
    """Accumulate ``seconds`` of device/pipeline time into ``phase``.

    No-op when ``CS230_OBS=0`` or the duration is non-positive (phases a
    batch never entered — e.g. a fully cache-hit stage — add nothing
    rather than minting zero-valued cells churn)."""
    if not _enabled():
        return
    s = float(seconds)
    if s <= 0.0:
        return
    REGISTRY.counter(DEVICE_SECONDS).inc(s, phase=phase)


def record_batch_device_seconds(
    compile_s: float, stage_s: float, run_s: float, fetch_s: float
) -> None:
    """Attribute one executed batch's phase totals (TrialRunResult's
    timers). ``dispatch`` = the device window minus the blocking fetches
    inside it, clamped at zero — the same decomposition the synthesized
    trace phases use (executor._record_batch_phases)."""
    if not _enabled():
        return
    device_seconds("compile", compile_s)
    device_seconds("stage", stage_s)
    device_seconds("dispatch", max(float(run_s) - float(fetch_s), 0.0))
    device_seconds("fetch", fetch_s)


def phase_totals() -> Dict[str, float]:
    """Current per-phase accumulations (tests / the cash-in report)."""
    c = REGISTRY.counter(DEVICE_SECONDS)
    return {p: c.value(phase=p) for p in PHASES}


class DeviceProfiler:
    """One-at-a-time programmatic ``jax.profiler`` capture.

    ``start()`` opens a trace into ``<journal_dir>/profile/<tag>`` and
    ``stop()`` closes it; both record flight-recorder events and feed
    ``tpuml_profile_captures_total``. A second ``start()`` while a capture
    is open is refused (the profiler is process-global state) — callers
    get a structured error instead of a jax exception."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._active: Optional[Dict[str, Any]] = None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            if self._active is None:
                return {"active": False}
            return {"active": True, **self._active}

    def start(self, tag: Optional[str] = None) -> Dict[str, Any]:
        """Begin a capture. Returns ``{status: "started", trace_dir: ...}``
        or a structured error dict (``status: "error"``) whose ``reason``
        tells the transport layer what happened: ``disabled`` (valve off
        → 503), ``busy`` (capture already open → 409), or ``backend``
        (the profiler/filesystem refused → 500)."""
        if not _enabled():
            return {
                "status": "error",
                "reason": "disabled",
                "message": "observability disabled (CS230_OBS=0)",
            }
        tag = _sanitize_tag(tag) or time.strftime("%Y%m%d-%H%M%S")
        trace_dir = os.path.join(journal_dir(), "profile", tag)
        with self._lock:
            if self._active is not None:
                return {
                    "status": "error",
                    "reason": "busy",
                    "message": "capture already active",
                    **self._active,
                }
            try:
                import jax

                os.makedirs(trace_dir, exist_ok=True)
                jax.profiler.start_trace(trace_dir)
            except Exception as e:  # noqa: BLE001 — surface, don't crash the server
                return {"status": "error", "reason": "backend",
                        "message": f"{type(e).__name__}: {e}"}
            self._active = {
                "tag": tag,
                "trace_dir": trace_dir,
                "started_ts": time.time(),
            }
            info = dict(self._active)
        record_event("profile.start", tag=tag, trace_dir=trace_dir)
        return {"status": "started", **info}

    def stop(self) -> Dict[str, Any]:
        """Finish the active capture. Returns ``{status: "stopped",
        trace_dir, duration_s, n_files}`` or an error when none is
        active. A FAILED stop (e.g. the dump filesystem filled up) keeps
        the capture marked active so it can be retried — unless the
        backend reports no session is running, in which case the handle
        is cleared (nothing is left to stop)."""
        with self._lock:
            if self._active is None:
                return {"status": "error", "reason": "idle",
                        "message": "no active capture"}
            info = self._active
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                # jax's "no profile/trace running" means the session died
                # underneath us — clearing the handle is the only way out;
                # any other failure keeps it active for a retry
                session_gone = "no profile" in str(e).lower() or \
                    "no trace" in str(e).lower()
                if session_gone:
                    self._active = None
                record_event("profile.stop", tag=info["tag"], error=str(e))
                return {"status": "error",
                        "reason": "idle" if session_gone else "backend",
                        "message": f"{type(e).__name__}: {e}",
                        **info}
            self._active = None
        duration = time.time() - info["started_ts"]
        n_files = sum(len(fs) for _, _, fs in os.walk(info["trace_dir"]))
        REGISTRY.counter(
            "tpuml_profile_captures_total",
        ).inc()
        record_event(
            "profile.stop", tag=info["tag"], trace_dir=info["trace_dir"],
            duration_s=round(duration, 3), n_files=n_files,
        )
        return {
            "status": "stopped",
            "tag": info["tag"],
            "trace_dir": info["trace_dir"],
            "duration_s": duration,
            "n_files": n_files,
        }


def _sanitize_tag(tag: Optional[str]) -> Optional[str]:
    """Capture tags come off the wire and become a path component: keep
    [-._a-zA-Z0-9] only, so a request cannot traverse out of the journal
    dir."""
    if not tag:
        return None
    clean = "".join(c for c in str(tag) if c.isalnum() or c in "-._")
    return clean.strip(".") or None


#: the process-global profiler the /profile routes drive
PROFILER = DeviceProfiler()
