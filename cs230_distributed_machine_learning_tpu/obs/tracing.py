"""Dapper-style job tracing: spans with IDs propagated over the REST plane.

One job's timeline stitches across the whole chain — client
(``client/manager.py``) → coordinator REST server (``runtime/server.py``) →
scheduler placement → executor batch → remote agent (``runtime/agent.py``)
— via a single ``trace_id``:

- the client mints the id and sends it as an ``X-Trace-Id`` header;
- the server middleware activates it for the request (contextvar), so
  every span opened inside the handler inherits it;
- the coordinator stamps it into each subtask spec, so it rides the task
  bus / ``GET /next_tasks`` long-poll to worker agents;
- agents record executor spans into their own process-local tracer and
  ship them back with ``POST /trace_spans/<wid>`` (``X-Trace-Id`` on the
  request), where the coordinator's tracer ingests them.

``GET /trace/<job_id>`` then returns the ordered span tree. Spans live in
a bounded per-trace ring (oldest whole traces evicted) and, best-effort,
in a JSONL journal under the storage root — the permanent answer to
"where did job X spend its time" that VERDICT weaknesses 1/4/5 lacked.

Everything here is valve-gated by ``CS230_OBS`` (see obs/__init__.py):
disabled, ``span()`` yields a shared no-op and records nothing.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

#: active (trace_id, span_id) for the current thread/context — the
#: propagation vehicle between nested spans and across the server
#: middleware -> handler boundary. New threads start empty: cross-thread
#: hops (coordinator job threads, executor workers) pass trace ids
#: explicitly (thread args / task specs).
_CTX: contextvars.ContextVar = contextvars.ContextVar("tpuml_trace", default=None)

#: tracer override for the current context — lets a worker agent route its
#: executor spans into a private tracer (drained and shipped over REST)
#: while the rest of the process keeps the global one
_SINK: contextvars.ContextVar = contextvars.ContextVar("tpuml_tracer", default=None)

#: max whole traces kept; oldest trace evicted wholesale (a job's spans
#: stay together — partial timelines are worse than absent ones)
_MAX_TRACES = 256
#: max spans within one trace (runaway instrumentation guard)
_MAX_SPANS_PER_TRACE = 2048
#: job-id -> trace-id bindings kept
_MAX_JOBS = 1024

TRACE_HEADER = "X-Trace-Id"
#: parent-span propagation for multi-hop stitching: a front end sends the
#: span id of its open ``frontend.proxy`` span so the shard's
#: ``http.<endpoint>`` span nests under it instead of surfacing as a
#: second root (docs/OBSERVABILITY.md "Critical path & trace export")
PARENT_HEADER = "X-Parent-Span"


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:8]


def _enabled() -> bool:
    return os.environ.get("CS230_OBS", "1") != "0"


def _journal_enabled() -> bool:
    return os.environ.get("CS230_OBS_JOURNAL", "1") != "0"


def _journal_max_bytes() -> int:
    """Size cap per journal file (spans.jsonl / events.jsonl) before a
    rotation. Long-lived coordinators used to grow spans.jsonl without
    bound across sessions; now the file rolls to ``<name>.1`` (one rotated
    generation kept) when it crosses the cap."""
    try:
        return int(float(os.environ.get("CS230_JOURNAL_MAX_MB", "64")) * 1e6)
    except ValueError:
        return int(64e6)


def journal_dir() -> str:
    """Resolve the journal directory: ``CS230_JOURNAL_DIR`` pins it to one
    place regardless of the configured storage root — CI uses it to
    collect every span/event of a test run (whose fixtures re-root storage
    per test) into a single uploadable artifact (deploy/ci.sh)."""
    d = os.environ.get("CS230_JOURNAL_DIR")
    if not d:
        from ..utils.config import get_config

        d = get_config().storage.journal_dir
    return d


def journal_append(basename: str, obj: Dict[str, Any]) -> None:
    """Best-effort size-rotated JSONL append under the journal dir — the
    shared writer behind the span journal (``spans.jsonl``) and the flight
    recorder's event journal (``events.jsonl``). Volume is low (dozens of
    lines per job), so open-append-close per line is acceptable; any
    filesystem failure silently drops the line (the in-process rings stay
    authoritative)."""
    if not _journal_enabled():
        return
    try:
        d = journal_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, basename)
        try:
            if os.path.getsize(path) > _journal_max_bytes():
                os.replace(path, path + ".1")
        except OSError:
            pass  # first write: no file to rotate yet
        with open(path, "a") as f:
            f.write(json.dumps(obj, default=str) + "\n")
    except Exception:  # noqa: BLE001 — observability must never fail a job
        pass


class SpanHandle:
    """Mutable view of an open span: add attributes mid-flight
    (``sp.attrs["n_subtasks"] = 12``) or read ids for manual child spans."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start", "attrs")

    def __init__(self, trace_id, span_id, parent_id, name, start, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.attrs = attrs


class Tracer:
    """Bounded in-process span store, indexed by trace id.

    ``pending=True`` additionally queues every recorded span into a drain
    buffer — the worker-agent mode, where spans are shipped to the
    coordinator over REST after each batch (``drain()``).
    """

    def __init__(self, *, pending: bool = False, journal: bool = True):
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, List[Dict[str, Any]]]" = (
            collections.OrderedDict()
        )
        self._jobs: "collections.OrderedDict[str, str]" = collections.OrderedDict()
        self._pending: Optional[collections.deque] = (
            collections.deque(maxlen=4096) if pending else None
        )
        self._journal = journal

    # ---------------- recording ----------------

    def record(self, span: Dict[str, Any]) -> None:
        """Store one finished span dict (keys: trace_id, span_id, parent_id,
        name, start, end, attrs, process)."""
        tid = span.get("trace_id")
        if not tid:
            return
        dropped: Dict[str, int] = {}
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                spans = []
                self._traces[tid] = spans
                while len(self._traces) > _MAX_TRACES:
                    # whole-trace eviction, oldest first (insertion /
                    # last-touch order); every span of the victim is a drop
                    _vid, victim = self._traces.popitem(last=False)
                    dropped["trace_evicted"] = (
                        dropped.get("trace_evicted", 0) + len(victim)
                    )
            else:
                self._traces.move_to_end(tid)
            if len(spans) < _MAX_SPANS_PER_TRACE:
                spans.append(span)
            else:
                # runaway-instrumentation guard hit: the span never lands
                # in the ring (the journal line below still writes)
                dropped["trace_full"] = dropped.get("trace_full", 0) + 1
            if self._pending is not None:
                self._pending.append(span)
        if dropped:
            self._count_dropped(dropped)
        if self._journal:
            self._journal_write(span)

    @staticmethod
    def _count_dropped(dropped: Dict[str, int]) -> None:
        """Surface ring overflow (``tpuml_trace_spans_dropped_total``,
        labeled by reason) — a silent drop reads as 'the job recorded
        nothing there', which is exactly the lie the critical-path
        engine's ``untraced`` contract exists to avoid. Lazy import:
        metrics imports nothing from here, but the facade imports both,
        so the top level must stay acyclic."""
        try:
            from .metrics import REGISTRY

            for reason, n in dropped.items():
                REGISTRY.counter("tpuml_trace_spans_dropped_total").inc(
                    n, reason=reason
                )
        except Exception:  # noqa: BLE001 — accounting must not fail recording
            pass

    def ingest(self, spans: List[Dict[str, Any]]) -> int:
        """Accept remotely-recorded spans (the /trace_spans route). Returns
        how many were stored; malformed entries are dropped, not fatal."""
        n = 0
        for s in spans or []:
            if isinstance(s, dict) and s.get("trace_id") and s.get("name"):
                self.record(dict(s))
                n += 1
        return n

    def drain(self) -> List[Dict[str, Any]]:
        """Pop all pending-export spans (agent mode)."""
        if self._pending is None:
            return []
        out: List[Dict[str, Any]] = []
        with self._lock:
            while self._pending:
                out.append(self._pending.popleft())
        return out

    # ---------------- job binding / reads ----------------

    def bind_job(self, job_id: str, trace_id: str) -> None:
        with self._lock:
            self._jobs[job_id] = trace_id
            while len(self._jobs) > _MAX_JOBS:
                self._jobs.popitem(last=False)

    def trace_for_job(self, job_id: str) -> Optional[str]:
        with self._lock:
            return self._jobs.get(job_id)

    def spans_for(self, trace_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(s) for s in self._traces.get(trace_id, [])]

    def traces(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def tree(self, trace_id: str) -> List[Dict[str, Any]]:
        """Span forest for a trace: children nested under parents, siblings
        ordered by start time. Spans whose parent never arrived (e.g. a
        remote hop that predates ingestion) surface as roots — a partial
        timeline beats a dropped one."""
        spans = self.spans_for(trace_id)
        by_id = {s["span_id"]: {**s, "children": []} for s in spans}
        roots: List[Dict[str, Any]] = []
        for node in by_id.values():
            parent = by_id.get(node.get("parent_id"))
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)

        def _sort(nodes):
            nodes.sort(key=lambda n: (n.get("start") or 0, n["span_id"]))
            for n in nodes:
                _sort(n["children"])

        _sort(roots)
        return roots

    # ---------------- journal ----------------

    def _journal_write(self, span: Dict[str, Any]) -> None:
        """Size-rotated JSONL append under the storage journal dir (see
        :func:`journal_append`)."""
        journal_append("spans.jsonl", span)


#: the process-global tracer (coordinator side)
TRACER = Tracer()


def active_tracer() -> Tracer:
    return _SINK.get() or TRACER


@contextlib.contextmanager
def use_tracer(tracer: Tracer):
    """Route spans opened in this context into ``tracer`` (agent mode)."""
    token = _SINK.set(tracer)
    try:
        yield tracer
    finally:
        _SINK.reset(token)


def current_trace_id() -> Optional[str]:
    ctx = _CTX.get()
    return ctx[0] if ctx else None


def current_span_id() -> Optional[str]:
    """Span id of the innermost open span in this context (None outside any
    span) — the JSON log formatter stamps it into records so logs join
    metrics and traces on one id."""
    ctx = _CTX.get()
    return ctx[1] if ctx else None


@contextlib.contextmanager
def activate(trace_id: str, span_id: Optional[str] = None):
    """Make ``trace_id`` the ambient trace for this context — the server
    middleware (header -> context) and cross-thread handoffs use this."""
    token = _CTX.set((trace_id, span_id))
    try:
        yield
    finally:
        _CTX.reset(token)


class _NoopSpan:
    """Shared do-nothing handle for the disabled path: attribute writes
    land in throwaway slots."""

    __slots__ = ("attrs", "start")

    def __init__(self):
        self.attrs: Dict[str, Any] = {}
        self.start = 0.0

    trace_id = None
    span_id = None


_NOOP = _NoopSpan()


@contextlib.contextmanager
def span(
    name: str,
    *,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    tracer: Optional[Tracer] = None,
    process: Optional[str] = None,
    **attrs: Any,
):
    """Record a timed span. Trace/parent ids resolve from the ambient
    context unless given explicitly; with no ambient trace and no explicit
    id a fresh trace starts. Yields a :class:`SpanHandle` whose ``attrs``
    can be extended mid-span; the span records on exit (errors are noted
    in ``attrs['error']`` and re-raised)."""
    if not _enabled():
        _NOOP.attrs.clear()
        yield _NOOP
        return
    ctx = _CTX.get()
    tid = trace_id or (ctx[0] if ctx else None) or new_trace_id()
    pid = parent_id if parent_id is not None else (
        ctx[1] if ctx and ctx[0] == tid else None
    )
    sid = new_span_id()
    handle = SpanHandle(tid, sid, pid, name, time.time(), dict(attrs))
    token = _CTX.set((tid, sid))
    try:
        yield handle
    except BaseException as e:
        handle.attrs["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        _CTX.reset(token)
        t = tracer or active_tracer()
        t.record(
            {
                "trace_id": tid,
                "span_id": sid,
                "parent_id": pid,
                "name": name,
                "start": handle.start,
                "end": time.time(),
                "attrs": handle.attrs,
                "process": process or _process_tag(),
            }
        )


def record_phase(
    parent: Any,
    name: str,
    duration_s: float,
    *,
    start: Optional[float] = None,
    tracer: Optional[Tracer] = None,
    **attrs: Any,
) -> Optional[float]:
    """Record a synthesized child span from a measured duration — the
    vehicle for surfacing the trial engine's phase timers (compile /
    stage / dispatch / fetch) as timeline entries. ``parent`` is the
    enclosing SpanHandle; phases lay out sequentially from ``start``
    (default: parent start). Returns the phase's end time so callers can
    chain phases; no-op (returns None) when disabled or parent is a
    no-op span."""
    if not _enabled() or getattr(parent, "span_id", None) is None:
        return None
    t0 = parent.start if start is None else start
    t = tracer or active_tracer()
    t.record(
        {
            "trace_id": parent.trace_id,
            "span_id": new_span_id(),
            "parent_id": parent.span_id,
            "name": name,
            "start": t0,
            "end": t0 + max(float(duration_s), 0.0),
            "attrs": {"synthesized": True, **attrs},
            "process": _process_tag(),
        }
    )
    return t0 + max(float(duration_s), 0.0)


def _process_tag() -> str:
    return f"pid:{os.getpid()}"


_PROC_TOKEN: Optional[str] = None


def process_token() -> str:
    """Host-qualified identity of THIS process (``host:pid``) — the
    observation-source stamp on metrics/result messages. Bare pids are
    only unique per host, so a cross-host collision with the
    coordinator's pid would silently drop a remote agent's ingest."""
    global _PROC_TOKEN
    if _PROC_TOKEN is None:
        import socket

        _PROC_TOKEN = f"{socket.gethostname()}:{os.getpid()}"
    return _PROC_TOKEN
