"""Flight recorder: the *decision* axis of observability.

Spans (``.tracing``) answer "where did job X spend its time"; the metrics
registry (``.metrics``) answers "how much of Y happened". Neither can
reconstruct a scheduling DECISION after the fact — why subtask S landed on
worker W, what the predictor estimated, which workers were excluded or
penalized, why a lease was reclaimed, which attempt a retry superseded.
Since the fault-tolerance layer (docs/ROBUSTNESS.md) made the runtime
predictor load-bearing for correctness (lease deadlines, reclaim
decisions, speculation triggers, breaker evictions all derive from its
estimates), those decisions must be explainable.

The recorder is a bounded, thread-safe event log with two indices:

- a **firehose ring**: every event in arrival order, addressed by a
  monotonically increasing ``seq`` — served at ``GET /events?since=``.
- **per-subtask timelines**: events carrying ``job_id`` + ``subtask_id``
  are additionally indexed by that pair — served at
  ``GET /explain/<job_id>/<subtask_id>`` as the subtask's lifecycle
  (placement with full score breakdown -> lease grant -> reclaim/retry/
  speculation -> terminal result or quarantine).

Event schema (documented in docs/OBSERVABILITY.md "Flight recorder"):

    {"seq": 42, "ts": 1754..., "kind": "placement",
     "job_id": "...", "subtask_id": "...", "worker_id": "worker-1",
     "attempt": 0, "data": {...kind-specific...}}

Everything is valve-gated by ``CS230_OBS`` (one env read per call when
disabled — the same contract as the metric helpers, re-measured by
``benchmarks/obs_overhead_micro.py``). Events are also journaled to
``<journal_dir>/events.jsonl`` next to the span journal, through the same
size-rotating best-effort appender.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import REGISTRY
from .tracing import _enabled, journal_append

#: firehose depth — events kept for GET /events (oldest evicted)
_MAX_EVENTS = 8192
#: distinct (job_id, subtask_id) timelines kept (oldest evicted wholesale)
_MAX_SUBTASKS = 4096
#: events within one subtask's timeline (runaway-retry guard)
_MAX_EVENTS_PER_SUBTASK = 256


class FlightRecorder:
    """Bounded in-process lifecycle event store (see module docstring)."""

    def __init__(
        self,
        *,
        max_events: int = _MAX_EVENTS,
        max_subtasks: int = _MAX_SUBTASKS,
        journal: bool = True,
    ):
        self._lock = threading.Lock()
        self._seq = 0
        self._ring: collections.deque = collections.deque(maxlen=max_events)
        self._timelines: "collections.OrderedDict[Tuple[str, str], List[Dict[str, Any]]]" = (
            collections.OrderedDict()
        )
        self._max_subtasks = max_subtasks
        self._journal = journal

    # ---------------- recording ----------------

    def record(
        self,
        kind: str,
        *,
        job_id: Optional[str] = None,
        subtask_id: Optional[str] = None,
        worker_id: Optional[str] = None,
        attempt: Optional[int] = None,
        **data: Any,
    ) -> Optional[Dict[str, Any]]:
        """Append one lifecycle event. Returns the stored event (None when
        the valve is off). Events without a (job_id, subtask_id) pair —
        e.g. worker-scoped breaker transitions — land in the firehose
        only."""
        if not _enabled():
            return None
        event: Dict[str, Any] = {
            "ts": time.time(),
            "kind": kind,
            "job_id": job_id,
            "subtask_id": subtask_id,
            "worker_id": worker_id,
            "attempt": attempt,
            "data": data,
        }
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._ring.append(event)
            if job_id and subtask_id:
                key = (job_id, subtask_id)
                timeline = self._timelines.get(key)
                if timeline is None:
                    timeline = []
                    self._timelines[key] = timeline
                    while len(self._timelines) > self._max_subtasks:
                        self._timelines.popitem(last=False)
                else:
                    self._timelines.move_to_end(key)
                if len(timeline) < _MAX_EVENTS_PER_SUBTASK:
                    timeline.append(event)
        if self._journal:
            journal_append("events.jsonl", event)
        REGISTRY.counter("tpuml_recorder_events_total").inc(kind=kind)
        return event

    # ---------------- queries ----------------

    def timeline(
        self, job_id: str, subtask_id: str
    ) -> Optional[List[Dict[str, Any]]]:
        """All events for one subtask in seq order, or None when the pair
        was never recorded (the /explain 404 signal — distinct from an
        empty-but-known timeline, which cannot occur: a timeline exists
        only once its first event lands)."""
        with self._lock:
            timeline = self._timelines.get((job_id, subtask_id))
            return [dict(e) for e in timeline] if timeline is not None else None

    def job_subtasks(self, job_id: str) -> List[str]:
        """Subtask ids with a recorded timeline for ``job_id`` (the
        /explain discovery aid)."""
        with self._lock:
            return sorted(
                stid for jid, stid in self._timelines if jid == job_id
            )

    def events(
        self, since: int = 0, limit: int = 1000
    ) -> Tuple[List[Dict[str, Any]], int]:
        """Firehose read: events with ``seq > since`` (oldest first, at
        most ``limit``) plus the cursor for the next poll — the recorder's
        latest seq, EXCEPT when ``limit`` truncated the batch, where it is
        the last RETURNED event's seq (a poller resuming from the global
        latest would silently skip the truncated remainder). A ``since``
        older than the ring's tail silently skips the evicted gap (bounded
        memory beats complete history)."""
        with self._lock:
            out = [dict(e) for e in self._ring if e["seq"] > since]
            latest = self._seq
        limit = max(int(limit), 0)
        if len(out) > limit:
            out = out[:limit]
            return out, (out[-1]["seq"] if out else since)
        return out, latest

    def last_seq(self) -> int:
        with self._lock:
            return self._seq


#: the process-global recorder every runtime layer records into
RECORDER = FlightRecorder()


def record_event(kind: str, **kwargs: Any) -> None:
    """Module-level convenience over ``RECORDER.record`` (call sites read
    like the metric helpers: one import, one line, no-op when disabled)."""
    RECORDER.record(kind, **kwargs)
