"""Capacity signals: derive desired_workers / desired_shards from telemetry.

ROADMAP item 5(c): the observatory measures everything — RED route p99,
admission-queue depth, per-worker queue/load books, predictor-priced
backlog — but nothing ever turned those measurements into a capacity
decision. :class:`CapacitySignals` folds them into two gauges an
EXTERNAL autoscaler (deploy/) can act on:

- ``tpuml_autoscale_desired_workers`` — how many workers this shard
  should have. Sized so the predictor-priced backlog (every worker's
  load book is a sum of RuntimePredictor estimates, plus unplaced
  pending subtasks priced at the mean queued estimate) drains within
  ``autoscale_horizon_s``; bumped past the live count under PRESSURE
  (admission rejections within the window, an admission cap saturated,
  or route p99 over its SLO) because a fleet that is rejecting work or
  missing latency SLOs needs capacity regardless of what the backlog
  arithmetic says.
- ``tpuml_autoscale_desired_shards`` — how many coordinator shards the
  FLEET should run, sized so in-flight jobs sit at
  ``autoscale_target_fill`` of the (per-shard-carved) admission caps.

Hysteresis (the half that makes the signal actuatable): scale-UP
publishes immediately; scale-DOWN only after the raw signal has held
below the live count for ``autoscale_downscale_hold_s`` AND only as far
as the drain path can absorb — a worker is only removable when it is
idle (empty queue book), because removal drains through the existing
lease/evict/requeue machinery and yanking a loaded worker just converts
its queue into retries. Until both hold, the gauge reports the live
count and the ``GET /autoscale`` body says why (``scale_down_held``).

Driven by the engine sweep (cluster mode) and by ``/metrics/prom`` /
``/autoscale`` reads (direct mode has no sweep), throttled by
``autoscale_interval_s``. Fleet view: the front end sums per-shard
bodies at ``GET /autoscale`` (runtime/frontend.py).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, Optional

from .metrics import REGISTRY, Gauge
from .slo import windowed_rate
from .tracing import _enabled

__all__ = ["CapacitySignals"]

#: routes whose latency is their contract (long-poll, SSE, bulk
#: transfer, ?wait= holds) — never a pressure signal
_NON_SLO_ROUTES = {
    "next_tasks", "train_status", "dataset", "download_data",
    "download_model", "metrics", "preprocess",
}


def _route_p99_worst(now: float, max_age_s: float = 120.0) -> float:
    """Worst per-route p99 from the derived gauge (live registry cells,
    not the rings: the deriver runs right after refresh_route_p99 on the
    same sweep/scrape, so the cells ARE current)."""
    g = REGISTRY.get("tpuml_http_route_p99_seconds")
    if not isinstance(g, Gauge):
        return 0.0
    worst = 0.0
    for labels, value in g.cells():
        if labels.get("route") in _NON_SLO_ROUTES:
            continue
        worst = max(worst, float(value))
    return worst


class CapacitySignals:
    """Per-coordinator capacity deriver. One instance per Coordinator;
    evaluation reads the job store, the placement engine's books, and
    the registry, and is cheap enough to run at scrape cadence."""

    def __init__(self, coordinator):
        self._coord = coordinator
        self._lock = threading.Lock()
        self._report: Optional[Dict[str, Any]] = None
        self._last_eval = 0.0
        #: hysteresis clocks: when the raw signal first dropped below the
        #: live count (None while at/above)
        self._workers_below_since: Optional[float] = None
        self._shards_below_since: Optional[float] = None

    # ---------------- evaluation ----------------

    def report(self) -> Dict[str, Any]:
        """Last derived report (evaluating first if none exists yet) —
        the ``GET /autoscale`` body."""
        with self._lock:
            rep = self._report
        if rep is None:
            return self.evaluate(force=True)
        return rep

    def evaluate(
        self, *, now: Optional[float] = None, force: bool = False
    ) -> Dict[str, Any]:
        coord = self._coord
        svc = coord.config.service
        wall = time.time()
        now = wall if now is None else now
        with self._lock:
            if (
                not force
                and self._report is not None
                and wall - self._last_eval < svc.autoscale_interval_s
            ):
                return self._report
            self._last_eval = wall

        counts = coord.store.unfinished_counts()
        engine = coord.cluster.engine if coord.cluster is not None else None
        workers = engine.worker_snapshot() if engine is not None else {}
        live = len(workers)
        total_devices = (
            engine.total_devices() if engine is not None else 0
        )
        queue_depth = sum(
            int(w.get("queue_depth") or 0) for w in workers.values()
        )
        #: the load books ARE the predictor's pricing: every queued task
        #: added est/speed_factor seconds at placement time
        backlog_s = sum(
            float(w.get("load_seconds") or 0.0) for w in workers.values()
        )
        backlog_device_s = sum(
            float(w.get("load_seconds") or 0.0)
            * max(int(w.get("n_devices") or 1), 1)
            for w in workers.values()
        )
        idle_workers = sorted(
            wid for wid, w in workers.items()
            if int(w.get("queue_depth") or 0) == 0
            and float(w.get("load_seconds") or 0.0) <= 1e-9
        )
        # unplaced pending subtasks (admitted but not yet on a worker's
        # book) priced at the mean queued estimate — the predictor has no
        # task spec for them yet, the fleet mean is the best prior
        avg_est = (backlog_s / queue_depth) if queue_depth else 1.0
        unplaced = max(int(counts["pending_subtasks"]) - queue_depth, 0)
        backlog_total_s = backlog_s + unplaced * avg_est

        # ---- pressure signals ----
        p99 = _route_p99_worst(now)
        util = 0.0
        if svc.max_inflight_jobs > 0:
            util = max(util, counts["jobs"] / svc.max_inflight_jobs)
        if svc.admission_queue_watermark > 0:
            util = max(
                util,
                counts["pending_subtasks"] / svc.admission_queue_watermark,
            )
        reject_rate = None
        if _enabled():
            reject_rate = windowed_rate(
                "tpuml_jobs_rejected_total", svc.autoscale_horizon_s,
                now=now,
            )
        pressure = bool(
            (reject_rate or 0.0) > 0.0
            or util >= 1.0
            or (svc.route_p99_slo_s > 0 and p99 > svc.route_p99_slo_s)
        )
        # numeric per-shard pressure (the migration/steal trigger,
        # docs/ROBUSTNESS.md "Shard rebalancing"): dimensionless sum of
        # (a) backlog expressed in drain-horizons, (b) admission-cap
        # utilization, (c) a flat +1 while the shard is BURNING 429s —
        # rejecting work is hot no matter what the backlog arithmetic
        # says. 0 ≈ idle, ≥1 ≈ busy, ≥rebalance_hot_pressure ≈ shed load.
        horizon_v = max(float(svc.autoscale_horizon_s), 1e-6)
        shard_pressure = round(
            backlog_total_s / horizon_v
            + util
            + (1.0 if (reject_rate or 0.0) > 0.0 else 0.0),
            4,
        )

        # ---- desired workers ----
        horizon = max(float(svc.autoscale_horizon_s), 1e-6)
        demand = int(math.ceil(backlog_total_s / horizon))
        raw_workers = max(demand, int(svc.autoscale_min_workers), 0)
        if pressure:
            step = max(1, int(math.ceil(live * 0.5))) if live else 1
            raw_workers = max(raw_workers, live + step)
        raw_workers = min(raw_workers, int(svc.autoscale_max_workers))
        desired_workers, workers_held = self._hold_down(
            "workers", raw_workers, live, len(idle_workers), now,
            svc.autoscale_downscale_hold_s,
        )

        # ---- desired shards ----
        n_shards = max(int(coord.n_shards), 1)
        fill = min(max(float(svc.autoscale_target_fill), 1e-6), 1.0)
        job_util = (
            counts["jobs"] / svc.max_inflight_jobs
            if svc.max_inflight_jobs > 0 else 0.0
        )
        if (reject_rate or 0.0) > 0.0:
            # rejecting == beyond full, whatever the instantaneous count
            job_util = max(job_util, 1.0)
        raw_shards = max(int(math.ceil(n_shards * job_util / fill)), 1)
        # shards drain through job completion, not worker eviction: the
        # only drain gate is the hold window (a shard removal is a
        # journal-replay takeover, always absorbable)
        desired_shards, shards_held = self._hold_down(
            "shards", raw_shards, n_shards, n_shards, now,
            svc.autoscale_downscale_hold_s,
        )

        if _enabled():
            g = REGISTRY.gauge
            g("tpuml_autoscale_desired_workers").set(float(desired_workers))
            g("tpuml_autoscale_desired_shards").set(float(desired_shards))
            g("tpuml_autoscale_backlog_seconds").set(
                float(backlog_total_s)
            )
            g("tpuml_shard_pressure").set(float(shard_pressure))

        rep: Dict[str, Any] = {
            "desired_workers": desired_workers,
            "live_workers": live,
            "desired_shards": desired_shards,
            "n_shards": n_shards,
            "signals": {
                "backlog_seconds": round(backlog_total_s, 3),
                "backlog_device_seconds": round(backlog_device_s, 3),
                "queued_subtasks": queue_depth,
                "unplaced_subtasks": unplaced,
                "pending_subtasks": int(counts["pending_subtasks"]),
                "inflight_jobs": int(counts["jobs"]),
                "admission_utilization": round(util, 4),
                "reject_rate_per_s": (
                    None if reject_rate is None else round(reject_rate, 4)
                ),
                "route_p99_s": round(p99, 4),
                "route_p99_slo_s": svc.route_p99_slo_s,
                "total_devices": total_devices,
                "idle_workers": len(idle_workers),
                "pressure": pressure,
                "shard_pressure": shard_pressure,
            },
            "hysteresis": {
                "raw_desired_workers": raw_workers,
                "scale_down_held": bool(workers_held),
                "shards_scale_down_held": bool(shards_held),
                "hold_s": svc.autoscale_downscale_hold_s,
                "drainable_workers": len(idle_workers),
            },
            "horizon_s": svc.autoscale_horizon_s,
            "ts": now,
        }
        if coord.shard_id is not None:
            rep["shard"] = coord.shard_id
        with self._lock:
            self._report = rep
        return rep

    def _hold_down(
        self, key: str, raw: int, live: int, drainable: int, now: float,
        hold_s: float,
    ) -> "tuple[int, bool]":
        """Scale-down hysteresis: below-live signals publish only after
        holding ``hold_s``, and only as deep as ``drainable`` allows.
        Returns (published_value, held)."""
        attr = f"_{key}_below_since"
        with self._lock:
            if raw >= live or live <= 0:
                setattr(self, attr, None)
                return raw, False
            below_since = getattr(self, attr)
            if below_since is None:
                setattr(self, attr, now)
                below_since = now
        held_for = now - below_since
        if held_for < hold_s or drainable <= 0:
            return live, True
        stepped = max(raw, live - drainable)
        return stepped, stepped > raw
