"""Trial telemetry plane: in-fit learning curves.

This module is the shared vocabulary for the curve pipeline:

* **Device side** — kernels allocate a fixed-size trace buffer
  (``curve_points()`` slots, default 64) and write one sample every
  ``trace_stride(steps)`` iterations from inside their jitted scan
  bodies via :func:`trace_update`.  The buffer shape is independent of
  ``max_iter``, so the extra scan-carry cost is bounded and the AOT
  cache keys stay stable for a given valve setting.
* **Host side** — :func:`build_curve_record` trims the raw buffers to
  the populated prefix and emits a JSON-safe dict that rides the
  existing result/metrics transport; :func:`divergence` implements the
  numerical-health watchdog rule; :func:`last_k_slope` feeds the
  curve-aware ASHA rung decision (``CS230_ASHA_CURVE=1``).
* **Coordinator side** — :class:`CurveStore` is the bounded
  per-(job, subtask, rung) store behind ``GET /curves`` and the
  incremental ``curve`` SSE events.

Valves:

``CS230_CURVES``
    ``auto`` (default, capture on) | ``0`` (strict no-op: no extra
    scan outputs, no metrics, no store growth).  Joins every kernel's
    ``trace_salt`` so flipping it re-keys compiled executables.
``CS230_CURVE_POINTS``
    Trace buffer length (default 64, clamped to [4, 512]).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "curves_mode",
    "curves_enabled",
    "curve_points",
    "curves_salt",
    "trace_stride",
    "trace_update",
    "build_curve_record",
    "divergence",
    "last_k_slope",
    "CurveStore",
]

_POINTS_MIN = 4
_POINTS_MAX = 512


def curves_mode() -> str:
    """Current ``CS230_CURVES`` valve value (``auto`` or ``0``)."""
    v = os.environ.get("CS230_CURVES", "auto").strip().lower()
    return "0" if v in ("0", "off", "false") else "auto"


def curves_enabled() -> bool:
    return curves_mode() != "0"


def curve_points() -> int:
    """Trace buffer length; ``CS230_CURVE_POINTS`` clamped to [4, 512]."""
    try:
        p = int(os.environ.get("CS230_CURVE_POINTS", "64"))
    except ValueError:
        p = 64
    return max(_POINTS_MIN, min(_POINTS_MAX, p))


def curves_salt() -> tuple:
    """Joined into every kernel's ``trace_salt()`` so the valve (and
    buffer size) re-key AOT/disk/in-memory executable caches."""
    if not curves_enabled():
        return ("curves0",)
    return ("curves", curve_points())


def trace_stride(steps: int) -> int:
    """Sampling stride so a ``steps``-iteration scan fills at most
    ``curve_points()`` slots.  ``slot = t // stride``; the final
    iteration always lands in a valid slot because
    ``(steps - 1) // stride <= points - 1``."""
    steps = max(1, int(steps))
    return max(1, int(math.ceil(steps / float(curve_points()))))


def trace_update(buf, t, value, stride, *, active=None):
    """Write ``value`` into its slot of the trace buffer from inside a
    jitted scan body (last-sample-wins within a stride window).

    ``buf``: f32 array ``[P, *value.shape]``; ``t``: scalar iteration
    index (float or int); ``value``: sample; ``active``: optional bool
    mask broadcastable to ``value.shape`` — inactive lanes keep their
    previous sample so the trace tail freezes at convergence instead of
    collapsing to the resting value.
    """
    import jax.numpy as jnp

    idx = jnp.asarray(t, jnp.int32) // jnp.asarray(stride, jnp.int32)
    if active is not None:
        value = jnp.where(active, value, buf[idx])
    return buf.at[idx].set(value)


def _finite_list(arr) -> List[float]:
    """JSON-safe float list: non-finite values become ``None``."""
    out: List[Optional[float]] = []
    for v in arr:
        f = float(v)
        out.append(f if math.isfinite(f) else None)
    return out


def build_curve_record(
    channels: Dict[str, Any],
    stride: int,
    steps: int,
    *,
    tail: Optional[Sequence[float]] = None,
) -> Dict[str, Any]:
    """Assemble the JSON-safe per-trial curve record from raw trace
    buffers.

    ``channels`` maps channel name (``loss``/``gmax``/``score``) to an
    array shaped ``[S, P]`` (splits × trace slots) or ``[P]``; buffers
    are trimmed to the populated prefix ``ceil(steps / stride)``.
    ``tail`` is the per-split final score appended by the caller so the
    record is self-contained ("trace tail == final score" parity).
    """
    import numpy as np

    used = max(1, int(math.ceil(max(1, int(steps)) / float(max(1, int(stride))))))
    rec: Dict[str, Any] = {"v": 1, "stride": int(stride), "steps": int(steps)}
    nonfinite = False
    for name, buf in channels.items():
        a = np.asarray(buf, dtype=np.float64)
        if a.ndim == 1:
            a = a[None, :]
        a = a[:, : min(used, a.shape[1])]
        nonfinite = nonfinite or bool(np.any(~np.isfinite(a)))
        rec[name] = [_finite_list(row) for row in a]
    if tail is not None:
        t = np.asarray(tail, dtype=np.float64).reshape(-1)
        nonfinite = nonfinite or bool(np.any(~np.isfinite(t)))
        rec["tail"] = _finite_list(t)
    rec["nonfinite"] = nonfinite
    return rec


def _rows(rec: Dict[str, Any], channel: str) -> List[List[Optional[float]]]:
    rows = rec.get(channel)
    if not isinstance(rows, list) or not rows:
        return []
    if rows and not isinstance(rows[0], list):
        rows = [rows]
    return rows


def divergence(rec: Dict[str, Any], factor: float) -> bool:
    """Watchdog rule: a trial is diverged when any channel contains a
    non-finite sample, or when the trace tail of ``loss``/``gmax``
    exceeds ``factor`` × the median of its own early quarter (at least
    4 early points required so short traces never trip)."""
    if not isinstance(rec, dict):
        return False
    if rec.get("nonfinite"):
        return True
    import numpy as np

    for channel in ("loss", "gmax"):
        for row in _rows(rec, channel):
            vals = [v for v in row if v is not None]
            if any(v is None for v in row):
                return True
            n = len(vals)
            early_n = max(1, n // 4)
            if early_n < 4:
                continue
            early = np.median(np.abs(np.asarray(vals[:early_n], dtype=np.float64)))
            tail = abs(float(vals[-1]))
            if early > 0 and tail > float(factor) * early:
                return True
            if early == 0 and tail > float(factor):
                return True
    return False


def last_k_slope(values: Iterable[Optional[float]], k: int = 8) -> float:
    """Least-squares slope (per trace point) over the last ``k`` finite
    samples; 0.0 when fewer than 2 samples are available."""
    vals = [float(v) for v in values if v is not None and math.isfinite(float(v))]
    if len(vals) < 2:
        return 0.0
    tail = vals[-max(2, int(k)):]
    n = len(tail)
    xs = list(range(n))
    mx = (n - 1) / 2.0
    my = sum(tail) / n
    num = sum((x - mx) * (y - my) for x, y in zip(xs, tail))
    den = sum((x - mx) ** 2 for x in xs)
    return num / den if den else 0.0


class CurveStore:
    """Bounded, thread-safe per-(job, subtask, rung) curve store.

    Entries are deduped on ``(subtask_id, rung, attempt)`` — a curve
    re-delivered through both the result and metrics transports (or a
    retried fetch) counts once.  A monotone per-store version counter
    supports incremental SSE (``updates(job_id, since)``).  Per-job
    entry count is capped (oldest evicted) so a long sweep cannot grow
    the coordinator without bound.
    """

    def __init__(self, max_entries_per_job: int = 4096, max_jobs: int = 64):
        self._lock = threading.Lock()
        self._jobs: Dict[str, Dict[Tuple[str, int, int], Dict[str, Any]]] = {}
        self._order: List[str] = []  # job LRU
        self._version = 0
        self.max_entries_per_job = int(max_entries_per_job)
        self.max_jobs = int(max_jobs)

    def ingest(
        self,
        job_id: str,
        subtask_id: str,
        curve: Dict[str, Any],
        *,
        rung: int = 0,
        attempt: int = 0,
        diverged: bool = False,
    ) -> int:
        """Store one curve.  Returns the number of NEW trace points
        ingested (0 when the (subtask, rung, attempt) key was already
        present — callers use this for ``tpuml_curve_points_total``)."""
        if not isinstance(curve, dict):
            return 0
        key = (str(subtask_id), int(rung or 0), int(attempt or 0))
        with self._lock:
            per = self._jobs.get(job_id)
            if per is None:
                per = self._jobs[job_id] = {}
                self._order.append(job_id)
                while len(self._order) > self.max_jobs:
                    old = self._order.pop(0)
                    self._jobs.pop(old, None)
            elif key in per:
                return 0
            else:
                # refresh job LRU position
                try:
                    self._order.remove(job_id)
                except ValueError:
                    pass
                self._order.append(job_id)
            self._version += 1
            entry = {
                "subtask_id": key[0],
                "rung": key[1],
                "attempt": key[2],
                "curve": curve,
                "diverged": bool(diverged),
                "version": self._version,
            }
            per[key] = entry
            while len(per) > self.max_entries_per_job:
                oldest = min(per, key=lambda k: per[k]["version"])
                per.pop(oldest)
        return self._n_points(curve)

    def mark_diverged(self, job_id: str, subtask_id: str) -> None:
        with self._lock:
            per = self._jobs.get(job_id)
            if not per:
                return
            for key, entry in per.items():
                if key[0] == str(subtask_id):
                    self._version += 1
                    entry["diverged"] = True
                    entry["version"] = self._version

    @staticmethod
    def _n_points(curve: Dict[str, Any]) -> int:
        n = 0
        for channel in ("loss", "gmax", "score"):
            for row in _rows(curve, channel):
                n += len(row)
        return max(1, n)

    def job(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Full job view for ``GET /curves/<jid>``; None if unknown."""
        with self._lock:
            per = self._jobs.get(job_id)
            if per is None:
                return None
            entries = sorted(per.values(), key=lambda e: e["version"])
            return {
                "job_id": job_id,
                "n_curves": len(entries),
                "curves": [dict(e) for e in entries],
            }

    def subtask(self, job_id: str, subtask_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            per = self._jobs.get(job_id)
            if per is None:
                return None
            entries = [dict(e) for k, e in sorted(per.items(), key=lambda kv: kv[1]["version"]) if k[0] == str(subtask_id)]
        if not entries:
            return None
        return {"job_id": job_id, "subtask_id": str(subtask_id), "curves": entries}

    def updates(self, job_id: str, since: int) -> Tuple[List[Dict[str, Any]], int]:
        """Entries newer than ``since`` plus the new high-water mark —
        the incremental feed behind ``curve`` SSE events."""
        with self._lock:
            per = self._jobs.get(job_id) or {}
            fresh = sorted(
                (dict(e) for e in per.values() if e["version"] > int(since)),
                key=lambda e: e["version"],
            )
            mark = max((e["version"] for e in fresh), default=int(since))
        return fresh, mark

    def n_entries(self, job_id: Optional[str] = None) -> int:
        with self._lock:
            if job_id is not None:
                return len(self._jobs.get(job_id) or {})
            return sum(len(p) for p in self._jobs.values())

    def drop_job(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)
            try:
                self._order.remove(job_id)
            except ValueError:
                pass
