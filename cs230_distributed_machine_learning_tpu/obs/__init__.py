"""Observability facade: unified metrics registry + end-to-end job tracing.

Every runtime layer instruments through this module, never through
``metrics``/``tracing`` directly, because the facade owns the one global
valve:

    CS230_OBS=0   -> every helper below is a near-free no-op (one env
                     read); ``span()`` yields a shared inert handle.

The subsystems:

- :mod:`.metrics` — thread-safe counters/gauges/histograms exposed in
  Prometheus text format at ``GET /metrics/prom``. The full family
  catalog is registered eagerly below so scrapes see every name from the
  first request (documented in docs/OBSERVABILITY.md).
- :mod:`.tracing` — Dapper-style spans with ``trace_id`` propagated over
  the REST control plane (``X-Trace-Id`` header, task-spec stamping,
  agent span shipping); ``GET /trace/<job_id>`` returns the span tree.
- :mod:`.recorder` — the flight recorder: bounded per-subtask lifecycle
  events (placement score breakdowns, lease grant/reclaim, retries,
  speculation, quarantine) behind ``GET /explain/<job>/<subtask>`` and
  ``GET /events``.
- :mod:`.timeseries` — an embedded in-memory time-series ring sampling
  the registry on the sweep/scrape cadence; ``GET /metrics/history``.

Usage (hot paths pay one env check when disabled):

    from ..obs import obs_enabled, counter_inc, observe, span

    counter_inc("tpuml_subtasks_completed_total")
    observe("tpuml_executor_fetch_seconds", dt)
    with span("executor.batch", trace_id=tid, worker=wid) as sp:
        sp.attrs["n_dispatches"] = run.n_dispatches
"""

from __future__ import annotations

from typing import Optional, Sequence

from .critpath import (  # noqa: F401 — re-exported API
    compare as compare_critical_paths,
    critical_path,
)
from .devprof import (  # noqa: F401 — re-exported API
    PROFILER,
    DeviceProfiler,
    device_seconds,
    record_batch_device_seconds,
)
from .export import (  # noqa: F401 — re-exported API
    export_trace,
    to_otlp,
    to_perfetto,
)
from .metrics import (  # noqa: F401 — re-exported API
    CALIBRATION_BUCKETS,
    DEFAULT_BUCKETS,
    HTTP_BUCKETS,
    PLACEMENT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
)
from .recorder import (  # noqa: F401 — re-exported API
    RECORDER,
    FlightRecorder,
    record_event,
)
from .signals import CapacitySignals  # noqa: F401 — re-exported API
from .slo import (  # noqa: F401 — re-exported API
    AlertEngine,
    AlertRule,
    default_rules,
)
from .timeseries import (  # noqa: F401 — re-exported API
    TIMESERIES,
    TimeSeriesStore,
    timeseries_sample,
)
from .tracing import _enabled as _valve
from .tracing import (  # noqa: F401 — re-exported API
    PARENT_HEADER,
    TRACE_HEADER,
    TRACER,
    Tracer,
    activate,
    active_tracer,
    current_span_id,
    current_trace_id,
    new_trace_id,
    process_token,
    record_phase,
    span,
    use_tracer,
)


def obs_enabled() -> bool:
    """The master valve (single definition: tracing._enabled). Read per
    call (one env lookup) so tests and operators can flip ``CS230_OBS``
    on a live process."""
    return _valve()


# ---------------- valve-gated metric helpers ----------------


def counter_inc(name: str, amount: float = 1.0, **labels: str) -> None:
    if not obs_enabled():
        return
    REGISTRY.counter(name).inc(amount, **labels)


def gauge_set(name: str, value: float, **labels: str) -> None:
    if not obs_enabled():
        return
    REGISTRY.gauge(name).set(value, **labels)


def observe(
    name: str,
    value: float,
    buckets: Optional[Sequence[float]] = None,
    **labels: str,
) -> None:
    if not obs_enabled():
        return
    if buckets is not None:
        REGISTRY.histogram(name, buckets=buckets).observe(value, **labels)
    else:
        REGISTRY.histogram(name).observe(value, **labels)


def render_prometheus() -> str:
    return REGISTRY.render()


def refresh_route_p99() -> None:
    """Derive ``tpuml_http_route_p99_seconds{route=}`` from the request
    histogram (methods and codes pooled per route). Called at scrape and
    sweep time — the gauge exists so the embedded time-series ring can
    sample a p99 without sampling histogram buckets (obs/timeseries.py
    deliberately skips histograms)."""
    if not obs_enabled():
        return
    h = REGISTRY.get("tpuml_http_request_seconds")
    if not isinstance(h, Histogram):
        return
    routes = sorted({ls.get("route") for ls in h.labelsets() if ls.get("route")})
    g = REGISTRY.gauge("tpuml_http_route_p99_seconds")
    for route in routes:
        p99 = h.quantile_where(0.99, route=route)
        if p99 is not None:
            g.set(p99, route=route)


# ---------------- metric catalog ----------------
#
# Registered eagerly so every family is present (at zero) in the first
# scrape. Names, types, and meanings are documented in
# docs/OBSERVABILITY.md — keep the two in sync.

_CATALOG_REGISTERED = False


def register_catalog() -> None:
    global _CATALOG_REGISTERED
    if _CATALOG_REGISTERED:
        return
    _CATALOG_REGISTERED = True
    c, g, h = REGISTRY.counter, REGISTRY.gauge, REGISTRY.histogram
    c("tpuml_jobs_submitted_total", "Train jobs accepted by the coordinator")
    c("tpuml_jobs_completed_total", "Jobs finalized successfully")
    c("tpuml_jobs_failed_total", "Jobs finalized as failed")
    c(
        "tpuml_subtasks_dispatched_total",
        "Subtasks placed onto a worker by the scheduler (requeues re-count)",
    )
    c("tpuml_subtasks_completed_total", "Subtask executions that completed")
    c("tpuml_subtasks_failed_total", "Subtask executions that failed")
    c(
        "tpuml_subtasks_requeued_total",
        "Subtasks requeued off a dead/unsubscribed/evicted worker",
    )
    # ---- fault-tolerance layer (docs/ROBUSTNESS.md) ----
    c(
        "tpuml_subtasks_retried_total",
        "Subtask re-dispatches by the fault-tolerance layer, labeled by "
        "reason (failure|lease)",
    )
    c(
        "tpuml_subtasks_quarantined_total",
        "Subtasks quarantined after exhausting their retry budget or "
        "killing too many worker backends",
    )
    c(
        "tpuml_speculative_launched_total",
        "Speculative (backup) duplicates launched for straggling subtasks",
    )
    c(
        "tpuml_speculative_won_total",
        "Speculative duplicates whose result was accepted first",
    )
    c(
        "tpuml_speculative_wasted_total",
        "Duplicate results dropped for subtasks that were speculated "
        "(the losing copy's work)",
    )
    # ---- coordinator crash recovery + overload survival
    # (docs/ROBUSTNESS.md "Coordinator recovery") ----
    g(
        "tpuml_coordinator_recovery_seconds",
        "Wall time of the last boot recovery: journal replay plus "
        "in-flight job re-queue",
    )
    c(
        "tpuml_recovery_replayed_ops_total",
        "Journal operations replayed at boot, labeled by op",
    )
    c(
        "tpuml_recovery_jobs_resumed_total",
        "Unfinished jobs re-queued by resume_inflight after a restart",
    )
    c(
        "tpuml_recovery_subtasks_requeued_total",
        "Subtasks re-dispatched by resume_inflight (no journaled result)",
    )
    c(
        "tpuml_results_duplicate_dropped_total",
        "Duplicate terminal results dropped at ingest (requeue races, "
        "speculative losers, zombie attempts from before a restart)",
    )
    c(
        "tpuml_jobs_rejected_total",
        "Submits rejected by admission control (429), labeled by reason "
        "(global_inflight|session_inflight|queue_depth)",
    )
    c(
        "tpuml_overload_shed_total",
        "Optional work shed under overload, labeled by kind "
        "(speculative|prewarm)",
    )
    c(
        "tpuml_agent_reconnects_total",
        "Agent re-registrations after a coordinator restart "
        "(404 on /next_tasks)",
    )
    c(
        "tpuml_agent_results_buffered_total",
        "Results parked in an agent's local buffer during a coordinator "
        "outage",
    )
    c(
        "tpuml_agent_results_dropped_total",
        "Buffered results dropped because the agent's bounded buffer "
        "overflowed (the subtask re-runs via recovery/lease machinery)",
    )
    c(
        "tpuml_agent_orphan_results_total",
        "Results ingested from worker ids this coordinator never "
        "registered (agents flushing buffers across a restart)",
    )
    c("tpuml_agent_polls_total", "GET /next_tasks long-polls served")
    c(
        "tpuml_agent_tasks_pulled_total",
        "Subtasks handed to remote agents over /next_tasks",
    )
    c(
        "tpuml_agent_acks_total",
        "Task results acknowledged over POST /task_result",
    )
    c(
        "tpuml_executable_cache_hits_total",
        "In-process compiled-executable cache hits (trial engine)",
    )
    c(
        "tpuml_executable_cache_misses_total",
        "In-process compiled-executable cache misses (trial engine)",
    )
    c("tpuml_aot_cache_hits_total", "AOT disk-cache blob deserializations")
    c(
        "tpuml_aot_cache_misses_total",
        "AOT disk-cache misses (fresh trace/export)",
    )
    # ---- staged-dataset cache (docs/OBSERVABILITY.md "Data-plane
    # caching") ----
    c(
        "tpuml_stage_cache_hits_total",
        "Staged-dataset cache hits (a device-resident tensor reused "
        "across jobs)",
    )
    c(
        "tpuml_stage_cache_misses_total",
        "Staged-dataset cache misses (a staging upload was required)",
    )
    c(
        "tpuml_stage_cache_uploads_total",
        "Actual host->device staging uploads performed — exactly one per "
        "(dataset, device, staging form) under concurrent same-dataset "
        "jobs (single-flight contract)",
    )
    c(
        "tpuml_stage_cache_evictions_total",
        "Staged entries LRU-evicted under the device-memory budget",
    )
    g(
        "tpuml_stage_cache_bytes",
        "Device bytes held by the staged-dataset cache",
    )
    g(
        "tpuml_stage_cache_entries",
        "Entries resident in the staged-dataset cache",
    )
    # ---- elastic trial fabric (docs/ARCHITECTURE.md "Elastic trial
    # fabric") ----
    c(
        "tpuml_stage_cache_replications_total",
        "Mesh-shaped cache entries built by on-device broadcast/reshard "
        "(ICI) from an already-resident host copy — never a tunnel upload",
    )
    c(
        "tpuml_stage_cache_tunnel_bytes_total",
        "Bytes staged over the slow host->device tunnel (cache misses of "
        "tunnel-transport entries)",
    )
    c(
        "tpuml_stage_cache_ici_bytes_total",
        "Bytes moved device-to-device (ICI on TPU meshes) building "
        "mesh-shaped staged entries",
    )
    c(
        "tpuml_stage_cache_overflow_total",
        "Stage-budget overflows: every LRU survivor was pinned so the "
        "cache is committed beyond its budget (reason=pinned), or "
        "CS230_STAGE_STRICT refused an oversize upload (reason=strict)",
    )
    # ---- out-of-core row-block streaming (docs/ARCHITECTURE.md
    # "Out-of-core streaming") ----
    c(
        "tpuml_stream_blocks_total",
        "Row blocks served to streaming passes (cache hits + uploads)",
    )
    c(
        "tpuml_stream_bytes_total",
        "Bytes uploaded staging row blocks (post-compression, misses only)",
    )
    c(
        "tpuml_stream_upload_seconds_total",
        "Transfer wall spent uploading row blocks on the prefetch worker",
    )
    c(
        "tpuml_stream_wait_seconds_total",
        "Wall the streaming consumer spent blocked waiting for a block "
        "(the NON-hidden share of the transfer wall)",
    )
    c(
        "tpuml_stream_passes_total",
        "Complete passes over a streamed dataset's block set",
    )
    c(
        "tpuml_mesh_reshards_total",
        "Fleet mesh-generation bumps, labeled by reason "
        "(join|death|evict|unsubscribe)",
    )
    g(
        "tpuml_mesh_generation",
        "Current fleet mesh generation (bumped on every worker "
        "join/death/eviction; journal-replayed across coordinator "
        "restarts)",
    )
    g(
        "tpuml_mesh_devices_total",
        "Devices across every live worker's mesh slice (the data-plane "
        "width placements pack onto)",
    )
    # ---- background AOT prewarm (docs/OBSERVABILITY.md "Data-plane
    # caching") ----
    c(
        "tpuml_prewarm_warmed_total",
        "Prewarm hints warmed (executables constructed + tensors staged "
        "in the background), labeled by model",
    )
    c(
        "tpuml_prewarm_skipped_total",
        "Prewarm hints skipped, labeled by reason (duplicate|error)",
    )
    c("tpuml_http_requests_total", "REST requests served, labeled by endpoint")
    c("tpuml_trace_spans_ingested_total", "Remote spans accepted via /trace_spans")
    g("tpuml_workers_alive", "Workers currently registered with the scheduler")
    h(
        "tpuml_scheduler_placement_seconds",
        "Placement-decision latency (place() wall time)",
        buckets=PLACEMENT_BUCKETS,
    )
    h(
        "tpuml_executor_compile_seconds",
        "Per-bucket executable construction (trace/AOT-load + first-dispatch compile)",
    )
    h(
        "tpuml_executor_stage_seconds",
        "Host->device staging uploads (dataset/fold tensors, cache misses only)",
    )
    h(
        "tpuml_executor_dispatch_seconds",
        "Per-batch device execution window (dispatch to last result ready)",
    )
    h(
        "tpuml_executor_fetch_seconds",
        "Blocking device->host result fetches",
    )
    # ---- device cost accounting (docs/OBSERVABILITY.md "Cost accounting") ----
    c(
        "tpuml_executor_flops_total",
        "Model FLOPs executed per batch (analytical estimate; XLA "
        "cost-analysis fallback), labeled by model",
    )
    c(
        "tpuml_executor_bytes_total",
        "Bytes accessed per batch per XLA cost analysis, labeled by model",
    )
    g(
        "tpuml_executor_mfu",
        "Model-FLOP utilization of the most recent batch (fraction of "
        "device peak), labeled by model; absent on CPU backends",
    )
    g(
        "tpuml_device_hbm_bytes",
        "Local device memory, labeled kind=used|peak|limit (absent when "
        "the backend exposes no memory_stats)",
    )
    # ---- per-worker health (docs/OBSERVABILITY.md "Worker health") ----
    g(
        "tpuml_worker_ewma_batch_seconds",
        "EWMA of a worker's batch wall time, labeled by wid",
    )
    g(
        "tpuml_worker_heartbeat_age_seconds",
        "Seconds since a worker's last heartbeat, labeled by wid "
        "(refreshed at scrape)",
    )
    g(
        "tpuml_worker_failure_ratio",
        "Failed / total subtask outcomes per worker, labeled by wid",
    )
    g(
        "tpuml_worker_queue_depth",
        "Queued subtasks per worker, labeled by wid",
    )
    g(
        "tpuml_worker_straggler",
        "1 while a worker is flagged as a straggler, labeled by wid",
    )
    g(
        "tpuml_worker_breaker_state",
        "Circuit-breaker state per worker, labeled by wid (0 closed, "
        "1 half-open; evicted workers' cells are removed)",
    )
    # ---- predictor calibration (docs/OBSERVABILITY.md "Predictor
    # calibration") ----
    h(
        "tpuml_predictor_abs_rel_error",
        "Runtime-predictor error per observed subtask: |predicted - "
        "actual| / actual (dimensionless), labeled by model family",
        buckets=CALIBRATION_BUCKETS,
    )
    g(
        "tpuml_predictor_calibration_ratio",
        "EWMA of predicted/actual runtime per model family, labeled by "
        "model (1.0 = calibrated; >1 overestimates — leases too loose; "
        "<1 underestimates — false lease reclaims)",
    )
    # ---- flight recorder (docs/OBSERVABILITY.md "Flight recorder") ----
    c(
        "tpuml_recorder_events_total",
        "Lifecycle events recorded by the flight recorder, labeled by "
        "kind (placement, lease.reclaim, attempt, retry, quarantine, ...)",
    )
    # ---- perf observatory (docs/OBSERVABILITY.md "Perf observatory") ----
    c(
        "tpuml_executor_device_seconds_total",
        "Accumulated device/pipeline seconds per batch phase, labeled by "
        "phase (stage|compile|dispatch|fetch) — executor-local batches "
        "plus remote agents' batches at metrics ingest",
    )
    c(
        "tpuml_profile_captures_total",
        "Completed on-demand jax.profiler captures "
        "(POST /profile/start|stop)",
    )
    h(
        "tpuml_http_request_seconds",
        "Control-plane request latency, labeled by route (endpoint name), "
        "method, and code",
        buckets=HTTP_BUCKETS,
    )
    g(
        "tpuml_http_route_p99_seconds",
        "Per-route p99 request latency, derived from "
        "tpuml_http_request_seconds at scrape/sweep time so the embedded "
        "time-series ring can sample it, labeled by route",
    )
    g(
        "tpuml_sse_lag_seconds",
        "Delivery lag of the most recent SSE progress event beyond the "
        "stream's tick cadence (seconds a subscriber saw its event late)",
    )
    # ---- fleet health plane (docs/OBSERVABILITY.md "Fleet health
    # plane") ----
    g(
        "tpuml_autoscale_desired_workers",
        "Capacity signal: workers this coordinator should run, derived "
        "from predictor-priced backlog + admission/latency pressure with "
        "scale-down hysteresis (obs/signals.py; GET /autoscale)",
    )
    g(
        "tpuml_autoscale_desired_shards",
        "Capacity signal: coordinator shards the fleet should run, sized "
        "to autoscale_target_fill of the carved admission caps "
        "(obs/signals.py; GET /autoscale)",
    )
    g(
        "tpuml_autoscale_backlog_seconds",
        "Predictor-priced backlog the capacity deriver last folded: "
        "queued load books plus unplaced pending subtasks at the mean "
        "queued estimate (seconds)",
    )
    g(
        "tpuml_alert_firing",
        "1 while an alert rule is firing, 0 once resolved, labeled by "
        "rule (obs/slo.py; GET /alerts)",
    )
    c(
        "tpuml_alerts_fired_total",
        "alert.fire transitions of the SLO rules engine, labeled by rule",
    )
    c(
        "tpuml_alerts_resolved_total",
        "alert.resolve transitions of the SLO rules engine, labeled by "
        "rule",
    )


register_catalog()

__all__ = [
    "obs_enabled",
    "counter_inc",
    "gauge_set",
    "observe",
    "render_prometheus",
    "refresh_route_p99",
    "register_catalog",
    "REGISTRY",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "DEFAULT_BUCKETS",
    "PLACEMENT_BUCKETS",
    "HTTP_BUCKETS",
    "CALIBRATION_BUCKETS",
    "PROFILER",
    "DeviceProfiler",
    "device_seconds",
    "record_batch_device_seconds",
    "RECORDER",
    "FlightRecorder",
    "record_event",
    "TIMESERIES",
    "TimeSeriesStore",
    "timeseries_sample",
    "CapacitySignals",
    "AlertEngine",
    "AlertRule",
    "default_rules",
    "critical_path",
    "compare_critical_paths",
    "export_trace",
    "to_perfetto",
    "to_otlp",
    "TRACER",
    "Tracer",
    "TRACE_HEADER",
    "PARENT_HEADER",
    "span",
    "record_phase",
    "activate",
    "use_tracer",
    "active_tracer",
    "current_trace_id",
    "current_span_id",
    "new_trace_id",
    "process_token",
]
