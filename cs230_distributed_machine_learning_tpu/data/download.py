"""Dataset ingestion: kaggle / huggingface / local / builtin sources.

Capability parity with ``aws-prod/master/dataset_util.py:13-40`` (kaggle API
download, HF ``load_dataset`` -> CSV, local copy), plus the builtin no-egress
generators from data/datasets.py. External sources are import-gated so the
framework runs in hermetic environments.
"""

from __future__ import annotations

import os
import shutil
from typing import Iterator, Optional, Sequence

from .datasets import dataset_dir, materialize_builtin
from ..utils.logging import get_logger

logger = get_logger("tpuml.data")


def iter_csv_chunks(
    path: str,
    chunk_rows: int = 65536,
    columns: Optional[Sequence[str]] = None,
) -> Iterator["object"]:
    """Stream a CSV's rows in bounded-height DataFrame chunks.

    The ingest half of out-of-core streaming (data/streaming.py): a
    shared-volume CSV larger than host memory is consumed one
    ``chunk_rows`` slice at a time — ``data/preprocess.py``'s two-pass
    scaler folds these into running stats, then re-reads them as design
    blocks for ``CsvBlockSource``. Plain ``pandas.read_csv(chunksize=)``
    under the hood, so dtype inference and header handling match the
    whole-file reader byte for byte."""
    import pandas as pd

    reader = pd.read_csv(
        path, chunksize=max(int(chunk_rows), 1),
        usecols=list(columns) if columns is not None else None,
    )
    for chunk in reader:
        yield chunk


def download_dataset(
    dataset_url: str,
    dataset_name: str,
    dataset_type: str,
    root: Optional[str] = None,
) -> str:
    """Stage a dataset under <root>/datasets/<name>/. Returns the directory."""
    target = dataset_dir(dataset_name, root)
    os.makedirs(target, exist_ok=True)

    if dataset_type == "kaggle":
        try:
            import kaggle
        except ImportError as e:
            raise RuntimeError("kaggle package not available in this environment") from e
        except OSError as e:
            # the kaggle client authenticates at import time and raises
            # OSError when no credentials resolve; surface the deployment
            # story instead of a bare config error. (Download-time errors —
            # network, disk — propagate untouched below.)
            raise RuntimeError(
                "kaggle credentials not found: set KAGGLE_USERNAME/KAGGLE_KEY "
                "in the coordinator's environment or mount kaggle.json "
                "(KAGGLE_CONFIG_DIR) — see deploy/compose.yaml and "
                "deploy/tpu_vm_fleet.md (credentials are never baked into "
                "images, unlike the reference's Master.Dockerfile)"
            ) from e
        kaggle.api.dataset_download_files(dataset_url, path=target, unzip=True)
    elif dataset_type in ("huggingface", "hf"):
        try:
            from datasets import load_dataset
        except ImportError as e:
            raise RuntimeError("huggingface datasets package not available") from e
        ds = load_dataset(dataset_url)
        split = next(iter(ds))
        ds[split].to_csv(os.path.join(target, f"{dataset_name}.csv"))
    elif dataset_type == "local":
        if os.path.isdir(dataset_url):
            for name in os.listdir(dataset_url):
                if name.endswith(".csv"):
                    shutil.copy(os.path.join(dataset_url, name), target)
        elif os.path.isfile(dataset_url):
            shutil.copy(dataset_url, target)
        else:
            raise FileNotFoundError(dataset_url)
    elif dataset_type == "builtin":
        if materialize_builtin(dataset_name, root=root) is None:
            raise ValueError(f"Unknown builtin dataset {dataset_name!r}")
    else:
        raise ValueError(f"Unknown dataset_type {dataset_type!r}")

    logger.info("Staged dataset %s (%s) at %s", dataset_name, dataset_type, target)
    return target
