from .datasets import DatasetCache, collect_csv_metadata, load_table
from .preprocess import preprocess_dataframe

__all__ = ["DatasetCache", "collect_csv_metadata", "load_table", "preprocess_dataframe"]
