"""Dataset staging, metadata, and the host-side columnar cache.

Layout parity with the reference's shared-volume scheme
(``/mnt/efs/datasets/<id>/*.csv`` with a ``preprocessed/`` subdir the
workers *require* — ``aws-prod/worker/worker.py:406-408``,
``master.py:382-386``), rooted at the configurable storage root instead of
EFS. Two deliberate improvements over the reference:

- the reference re-reads the CSV from the shared volume for *every* subtask
  (``worker.py:424-425``); here a per-process ``DatasetCache`` parses the
  CSV once, encodes labels once, and keeps device-ready float32 arrays that
  all trials of all jobs reuse;
- builtin benchmark datasets (iris, covertype, synthetic generators) can be
  materialized locally without network egress.

Target convention preserved: last column is the label (``worker.py:428-429``).
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..models.base import TrialData
from ..utils.config import get_config

# parsed-columnar sidecar format: bump when the parse semantics change so
# stale blobs (e.g. pre-dating the 2^24 f32-label guard) are re-parsed
_SIDECAR_VERSION = 2


def dataset_dir(dataset_id: str, root: Optional[str] = None) -> str:
    root = root or get_config().storage.datasets_dir
    return os.path.join(root, dataset_id)


def find_csv(dataset_id: str, *, preprocessed: bool = False, root: Optional[str] = None):
    base = dataset_dir(dataset_id, root)
    if preprocessed:
        base = os.path.join(base, "preprocessed")
    hits = sorted(glob.glob(os.path.join(base, "*.csv")))
    return hits[0] if hits else None


def stage_arrays(dataset_id: str, X, y, *, root: Optional[str] = None) -> str:
    """Stage (X, y) as a preprocessed CSV dataset (target last column),
    atomically, skipping when already staged with the same row count —
    the shared staging block the benchmark harnesses and slow-parity
    tests previously each re-implemented. Returns the CSV path."""
    import numpy as np
    import pandas as pd

    n = len(X)
    ddir = os.path.join(dataset_dir(dataset_id, root), "preprocessed")
    os.makedirs(ddir, exist_ok=True)
    csv = os.path.join(ddir, f"{dataset_id}_preprocessed.csv")

    def _rows(path):
        with open(path) as f:
            return sum(1 for _ in f) - 1

    if not os.path.exists(csv) or _rows(csv) != n:
        df = pd.DataFrame(np.asarray(X))
        df["target"] = np.asarray(y)
        tmp = csv + f".tmp.{os.getpid()}"
        df.to_csv(tmp, index=False)
        os.replace(tmp, csv)  # atomic: a torn write can't pass the row check
    return csv


def collect_csv_metadata(path: str) -> Dict[str, Any]:
    """n_rows / n_cols / size_mb, the features the runtime predictor learns
    from (reference ``dataset_util.py:119-136``)."""
    size_mb = round(os.path.getsize(path) / (1024 * 1024), 2)

    from ..native import csv_dims

    dims = csv_dims(path)  # native mmap scan; None without a toolchain
    if dims is not None:
        return {"n_rows": dims[0], "n_cols": dims[1], "size_mb": size_mb}

    import pandas as pd

    df = pd.read_csv(path, nrows=1)
    n_cols = df.shape[1]
    with open(path, "rb") as f:
        n_rows = sum(1 for _ in f) - 1
    return {"n_rows": int(n_rows), "n_cols": int(n_cols), "size_mb": size_mb}


def load_table(path: str) -> Tuple[np.ndarray, np.ndarray, list]:
    """Load a staged CSV: features = all but last column, target = last.
    Non-numeric feature columns are label-encoded; returns (X, y_raw, columns).

    A parsed-columnar sidecar (<csv>.npz) is written on first load and reused
    while fresh — CSV stays the staging contract (reference layout), but the
    hot path never re-parses text. The cold parse itself is native
    (native/csv_loader.cpp: mmap + threaded float32 parse) when every column
    is numeric; tables with string columns fall back to pandas."""
    import pandas as pd

    sidecar = path + ".npz"
    if os.path.exists(sidecar) and os.path.getmtime(sidecar) >= os.path.getmtime(path):
        try:
            z = np.load(sidecar, allow_pickle=True)
            # format version gate: v2 added the 2^24 f32-label-precision
            # guard, so unversioned (pre-guard) sidecars must re-parse
            if int(z["version"]) >= _SIDECAR_VERSION:
                return z["X"], z["y"], list(z["columns"])
        except Exception:  # noqa: BLE001 — fall through to re-parse
            pass

    from ..native import csv_parse_f32

    parsed = csv_parse_f32(path)
    if parsed is not None and bool(parsed[1].all()) and parsed[0].shape[1] >= 1:
        mat, _ = parsed
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            columns = [
                c.strip().strip('"').strip("'")
                for c in f.readline().rstrip("\r\n").split(",")
            ]
        X, y = mat[:, :-1], mat[:, -1].astype(np.float64)
        # f32 can't represent integer labels beyond 2^24 exactly — a label
        # column in that range must take the pandas (int64) path or distinct
        # class ids would silently collide
        if not np.any(np.abs(y) >= 2**24):
            try:
                np.savez(
                    sidecar,
                    X=X,
                    y=y,
                    columns=np.asarray(columns, object),
                    version=_SIDECAR_VERSION,
                )
            except OSError:
                pass
            return X, y, columns

    df = pd.read_csv(path)
    X_df = df.iloc[:, :-1]
    y = df.iloc[:, -1].to_numpy()
    X_cols = []
    for col in X_df.columns:
        series = X_df[col]
        if pd.api.types.is_numeric_dtype(series):
            X_cols.append(series.to_numpy(dtype=np.float32))
        else:  # object / category / arrow-backed string: label-encode
            _, codes = np.unique(series.astype(str).to_numpy(), return_inverse=True)
            X_cols.append(codes.astype(np.float32))
    X = np.stack(X_cols, axis=1) if X_cols else np.zeros((len(df), 0), np.float32)
    try:
        np.savez(
            sidecar,
            X=X,
            y=y,
            columns=np.asarray(list(df.columns), object),
            version=_SIDECAR_VERSION,
        )
    except OSError:
        pass
    return X, y, list(df.columns)


# ---------------------------------------------------------------------------
# builtin datasets (no-egress benchmark data)
# ---------------------------------------------------------------------------


def materialize_builtin(name: str, root: Optional[str] = None) -> Optional[str]:
    """Write a builtin dataset as a staged CSV (both raw and preprocessed
    locations, since builtins are already clean). Returns the csv path."""
    import pandas as pd

    name_l = name.lower()
    if name_l == "iris":
        from sklearn.datasets import load_iris

        bunch = load_iris(as_frame=True)
        df = bunch.frame  # target already last column
    elif name_l in ("covertype", "covtype"):
        df = _synthetic_covertype()
    elif name_l == "titanic":
        df = _synthetic_titanic()
        # titanic ships raw (nulls, categoricals): the preprocess pipeline is
        # part of the demo flow, so only the raw CSV is staged
        base = dataset_dir(name, root)
        os.makedirs(base, exist_ok=True)
        raw_path = os.path.join(base, f"{name}.csv")
        if not os.path.exists(raw_path):
            df.to_csv(raw_path, index=False)
        return raw_path
    elif name_l.startswith("synthetic"):
        df = _synthetic_classification(name_l)
    else:
        return None

    base = dataset_dir(name, root)
    pre = os.path.join(base, "preprocessed")
    os.makedirs(pre, exist_ok=True)
    raw_path = os.path.join(base, f"{name}.csv")
    pre_path = os.path.join(pre, f"{name}_preprocessed.csv")
    if not os.path.exists(raw_path):
        df.to_csv(raw_path, index=False)
    if not os.path.exists(pre_path):
        df.to_csv(pre_path, index=False)
    return pre_path


def _synthetic_covertype(n: int = 116_202) -> "Any":
    """Covertype-shaped synthetic data (54 features, 7 classes). The real
    UCI download needs egress; this preserves the benchmark's shape/scale
    (n defaults to 20% of the real 581k rows to keep local staging fast —
    bench.py can regenerate at full scale)."""
    import pandas as pd
    from sklearn.datasets import make_classification

    X, y = make_classification(
        n_samples=n,
        n_features=54,
        n_informative=30,
        n_redundant=10,
        n_classes=7,
        n_clusters_per_class=2,
        random_state=0,
    )
    df = pd.DataFrame(X.astype(np.float32), columns=[f"f{i}" for i in range(54)])
    df["Cover_Type"] = y + 1
    return df


def _synthetic_titanic(n: int = 891) -> "Any":
    """Titanic-shaped synthetic table (same columns, nulls, and categorical
    mix as the Kaggle dataset the reference demos use) so the full
    download->preprocess(yaml)->train demo runs with zero egress."""
    import pandas as pd

    rng = np.random.RandomState(7)
    pclass = rng.choice([1, 2, 3], n, p=[0.24, 0.21, 0.55])
    sex = rng.choice(["male", "female"], n, p=[0.65, 0.35])
    age = np.round(rng.normal(29.7, 14.5, n).clip(0.4, 80), 1)
    age[rng.rand(n) < 0.2] = np.nan
    sibsp = rng.choice([0, 1, 2, 3, 4], n, p=[0.68, 0.23, 0.05, 0.03, 0.01])
    parch = rng.choice([0, 1, 2], n, p=[0.76, 0.13, 0.11])
    fare = np.round(np.exp(rng.normal(2.9, 1.0, n)).clip(0, 512), 4)
    embarked = rng.choice(["S", "C", "Q"], n, p=[0.72, 0.19, 0.09]).astype(object)
    embarked[rng.rand(n) < 0.002] = None
    # survival correlated with sex/class/age like the real data
    logit = 1.2 - 0.9 * (pclass - 1) + 2.4 * (sex == "female") - 0.015 * np.nan_to_num(age, nan=29.7)
    survived = (rng.rand(n) < 1 / (1 + np.exp(-logit))).astype(int)
    return pd.DataFrame(
        {
            "PassengerId": np.arange(1, n + 1),
            "Survived": survived,
            "Pclass": pclass,
            "Name": [f"Passenger {i}" for i in range(n)],
            "Sex": sex,
            "Age": age,
            "SibSp": sibsp,
            "Parch": parch,
            "Ticket": [f"T{100000+i}" for i in range(n)],
            "Fare": fare,
            "Cabin": [None] * n,
            "Embarked": embarked,
        }
    )


def _synthetic_classification(spec: str) -> "Any":
    """`synthetic[_<n>x<d>x<c>]` generator for tests/benchmarks."""
    import pandas as pd
    from sklearn.datasets import make_classification

    n, d, c = 10_000, 20, 2
    parts = spec.split("_")
    if len(parts) > 1:
        try:
            dims = parts[1].split("x")
            n, d = int(dims[0]), int(dims[1])
            c = int(dims[2]) if len(dims) > 2 else 2
        except (ValueError, IndexError):
            pass
    X, y = make_classification(
        n_samples=n,
        n_features=d,
        n_informative=max(2, d // 2),
        n_classes=c,
        random_state=0,
    )
    df = pd.DataFrame(X.astype(np.float32), columns=[f"f{i}" for i in range(d)])
    df["target"] = y
    return df


# ---------------------------------------------------------------------------
# columnar cache
# ---------------------------------------------------------------------------


class DatasetCache:
    """Parse-once cache of staged datasets as TrialData, keyed by dataset id
    and task kind. Classification labels are encoded by np.unique order —
    identical to sklearn's LabelEncoder ordering."""

    def __init__(self, root: Optional[str] = None):
        self._root = root
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[str, str], TrialData] = {}
        self._meta: Dict[str, Dict[str, Any]] = {}

    def resolve_csv(self, dataset_id: str) -> str:
        path = find_csv(dataset_id, preprocessed=True, root=self._root) or find_csv(
            dataset_id, root=self._root
        )
        if path is None:
            path = materialize_builtin(dataset_id, root=self._root)
        if path is None:
            raise FileNotFoundError(
                f"Dataset {dataset_id!r} not staged (and not a builtin). "
                f"Call download_data/preprocess first."
            )
        return path

    def metadata(self, dataset_id: str) -> Dict[str, Any]:
        with self._lock:
            if dataset_id not in self._meta:
                self._meta[dataset_id] = collect_csv_metadata(self.resolve_csv(dataset_id))
            return dict(self._meta[dataset_id])

    def get(self, dataset_id: str, task: str) -> TrialData:
        key = (dataset_id, task)
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        X, y_raw, _ = load_table(self.resolve_csv(dataset_id))
        if task == "classification":
            classes, y = np.unique(y_raw, return_inverse=True)
            data = TrialData(X=X, y=y.astype(np.int32), n_classes=len(classes))
        else:
            data = TrialData(X=X, y=y_raw.astype(np.float32), n_classes=0)
        with self._lock:
            self._cache[key] = data
        return data

    def invalidate(self, dataset_id: str) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == dataset_id]:
                del self._cache[key]
            self._meta.pop(dataset_id, None)


class FetchingDatasetCache(DatasetCache):
    """DatasetCache that fetches missing datasets from the coordinator over
    DCN (``GET /dataset/<id>``) — the multi-host replacement for the
    reference's shared EFS volume (docker-compose.yml:92-94, setup.sh:14-29):
    a kaggle/HF download or YAML preprocess staged on the coordinator host
    becomes reachable from every remote agent, fetched once and then served
    from the local staged layout.

    Resolution per lookup: local *preprocessed* copy -> cheap coordinator
    probe (``?probe=1``, JSON kind only) -> download when the coordinator
    holds something better than what's local (preprocessed beats raw) ->
    local raw/builtin staging. The probe runs on every DatasetCache miss
    (a handful per process), so a preprocess staged on the coordinator
    AFTER an agent's first raw fetch is picked up without a restart —
    nothing is negative-cached.
    """

    def __init__(self, coordinator_url: str, root: Optional[str] = None,
                 timeout_s: float = 120.0):
        super().__init__(root=root)
        self._url = coordinator_url.rstrip("/")
        self._timeout_s = timeout_s

    def resolve_csv(self, dataset_id: str) -> str:
        local_pre = find_csv(dataset_id, preprocessed=True, root=self._root)
        if local_pre is not None:
            return local_pre
        remote_kind = self._probe(dataset_id)
        if remote_kind is not None:
            local_raw = find_csv(dataset_id, root=self._root)
            if remote_kind == "raw" and local_raw is not None:
                return local_raw
            path = self._fetch(dataset_id)
            if path is not None:
                return path
        return super().resolve_csv(dataset_id)

    def _probe(self, dataset_id: str) -> Optional[str]:
        """Coordinator's staged kind for the dataset ('preprocessed'/'raw')
        or None when absent/unreachable."""
        import requests

        try:
            resp = requests.get(
                f"{self._url}/dataset/{dataset_id}",
                params={"probe": "1"},
                timeout=min(self._timeout_s, 15.0),
            )
            if resp.status_code == 404:
                return None
            resp.raise_for_status()
            return resp.json().get("kind", "raw")
        except Exception:  # noqa: BLE001
            return None

    def _fetch(self, dataset_id: str) -> Optional[str]:
        import requests

        from ..utils.logging import get_logger

        logger = get_logger("tpuml.data")
        try:
            resp = requests.get(
                f"{self._url}/dataset/{dataset_id}", timeout=self._timeout_s
            )
            if resp.status_code == 404:
                return None
            resp.raise_for_status()
        except Exception:  # noqa: BLE001
            logger.exception("Dataset fetch for %r failed; trying local staging",
                             dataset_id)
            return None
        kind = resp.headers.get("X-Dataset-Kind", "raw")
        base = dataset_dir(dataset_id, self._root)
        if kind == "preprocessed":
            out_dir = os.path.join(base, "preprocessed")
            out = os.path.join(out_dir, f"{dataset_id}_preprocessed.csv")
        else:
            out_dir = base
            out = os.path.join(out_dir, f"{dataset_id}.csv")
        os.makedirs(out_dir, exist_ok=True)
        tmp = f"{out}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(resp.content)
        os.replace(tmp, out)
        logger.info("Fetched dataset %s (%s, %d bytes) from coordinator",
                    dataset_id, kind, len(resp.content))
        return out
