"""Out-of-core row-block streaming: datasets bigger than the stage budget.

Every pre-16 workload staged the FULL design matrix on-device, so the
largest trainable dataset was bounded by device memory minus the stage
cache budget (``data/stage_cache.py``). The paper's task-farm model
(PAPER.md §2.1 — workers fit estimators over shared-volume CSVs of
arbitrary size) has no such ceiling, and the Pallas kernels already
iterate row tiles internally (``packed_nesterov_step`` streams Ab tiles
VMEM<->HBM); this module lifts that tile loop's outer level to
HBM<->host:

- **row-block plans** (``plan_blocks``): the dataset is tiled into
  uniform row blocks (``CS230_STREAM_BLOCK_ROWS`` pins the block height;
  the default sizes blocks at ~1/8 of the stage-cache budget so a
  double-buffered pair plus the fold tensors stay well inside it). The
  last block is zero-padded to the uniform height — solver drivers see
  zero sample weights on pad rows, which contribute exactly nothing to
  gradients, histograms, or scores.
- **blocks are ordinary staged forms**: block ``i`` lives in the
  multi-tenant stage cache under
  ``(dataset_fingerprint, host_signature(), "block", *form, i)`` — so
  concurrent tenants streaming the same dataset share uploads
  (single-flight), repeat passes are cache hits while the budget allows,
  and LRU eviction reclaims blocks the pass has already consumed.
- **double-buffered upload** (``RowBlockStreamer``): a one-worker
  prefetch thread stages block ``i+1`` (host fetch -> optional
  ``CS230_STAGE_DTYPE`` compression -> ``device_put``) while the caller
  computes on block ``i``, hiding the transfer wall behind compute.
  In-flight and prefetched blocks hold an explicit cache ref
  (``StagedDatasetCache.acquire``/``release``) so LRU pressure from
  other tenants can never drop them mid-pass.
- **per-host block sets** (``host_block_set``): on a 2-D row-sharded
  mesh each host streams a disjoint contiguous range of blocks — the
  PR 15 ``"rows"`` mesh staging form generalized from "one shard per
  host" to "one block set per host" (block keys already carry
  ``host_signature()``).
- **disk-backed blocks** (``CsvBlockSource``): chunked CSV ingest
  (``data/download.py::iter_csv_chunks`` + the two-pass scaler in
  ``data/preprocess.py``) feeds blocks without ever materializing the
  full matrix on the host.

Valves (all joined into kernel ``trace_salt`` by the consuming kernels):

- ``CS230_STREAM``: ``auto`` (default — stream when the legacy staged
  form would exceed half the stage budget), ``0``/``off`` (legacy
  single-shot staging, bit-for-bit), ``1``/``force``.
- ``CS230_STREAM_BLOCK_ROWS``: pin the block height.
- ``CS230_STREAM_DOUBLE_BUFFER=0``: disable the prefetch worker (the
  A/B lever the overlap benchmark measures).

Observability: ``tpuml_stream_*`` counters, one ``stage.stream``
flight-recorder event per pass, and devprof's ``stream`` phase
(transfer wall hidden behind compute) — docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..obs import counter_inc, record_event
from ..utils.logging import get_logger
from .stage_cache import STAGE_CACHE, _tree_nbytes, budget_bytes

logger = get_logger("tpuml.streaming")

#: floor on the auto block height — below this the per-block dispatch
#: overhead dominates any transfer overlap
_MIN_BLOCK_ROWS = 256

#: auto-sized blocks target this fraction of the stage-cache budget, so a
#: double-buffered pair (in-flight + prefetched) plus the padded fold
#: tensors and a few consumed-but-unevicted blocks stay inside it
_BLOCK_BUDGET_FRACTION = 8

#: CS230_STREAM=auto streams when the legacy single-shot staged form
#: would exceed this fraction of the stage budget (past it, one dataset
#: crowds out every other tenant even when it technically fits)
_AUTO_BUDGET_FRACTION = 0.5


def stream_mode() -> str:
    """Resolve ``CS230_STREAM``: ``off`` | ``auto`` | ``force``. Read per
    call so tests can flip it live; consuming kernels fold the RESOLVED
    mode into ``trace_salt`` so every executable cache keys on it."""
    raw = os.environ.get("CS230_STREAM", "auto").lower()
    if raw in ("0", "off", "false"):
        return "off"
    if raw in ("1", "force"):
        return "force"
    return "auto"


def stream_double_buffer() -> bool:
    """CS230_STREAM_DOUBLE_BUFFER=0 disables the prefetch worker — the
    benchmark's A/B lever for the overlap measurement."""
    return os.environ.get("CS230_STREAM_DOUBLE_BUFFER", "1") != "0"


def should_stream(nbytes: int) -> bool:
    """Stream a dataset whose legacy single-shot staged footprint is
    ``nbytes``? ``force``/``off`` override; ``auto`` compares against
    half the stage-cache budget."""
    mode = stream_mode()
    if mode == "off":
        return False
    if mode == "force":
        return True
    return float(nbytes) > _AUTO_BUDGET_FRACTION * budget_bytes()


@dataclasses.dataclass(frozen=True)
class BlockPlan:
    """Uniform row-block tiling of an ``n``-row dataset: ``n_blocks``
    blocks of ``rows`` rows each; the last block is zero-padded up to
    ``rows`` (consumers see zero sample weights on pad rows)."""

    n: int
    rows: int
    n_blocks: int

    @property
    def n_pad(self) -> int:
        return self.rows * self.n_blocks

    def start(self, i: int) -> int:
        return i * self.rows

    def size(self, i: int) -> int:
        """Real (unpadded) rows of block ``i``."""
        return min(self.n, (i + 1) * self.rows) - i * self.rows

    def block_ids(self) -> range:
        return range(self.n_blocks)


def plan_blocks(n: int, row_bytes: int, rows: Optional[int] = None) -> BlockPlan:
    """Tile ``n`` rows of ``row_bytes`` bytes each into uniform blocks.
    ``CS230_STREAM_BLOCK_ROWS`` (or the ``rows`` argument) pins the block
    height; the default targets ``budget_bytes() / 8`` per block."""
    if rows is None:
        env = os.environ.get("CS230_STREAM_BLOCK_ROWS")
        if env:
            try:
                rows = max(int(float(env)), 1)
            except ValueError:
                rows = None
    if rows is None:
        target = max(budget_bytes() // _BLOCK_BUDGET_FRACTION, 1)
        rows = max(_MIN_BLOCK_ROWS, int(target // max(int(row_bytes), 1)))
    rows = max(1, min(int(rows), max(int(n), 1)))
    n_blocks = max(1, -(-int(n) // rows))
    return BlockPlan(n=int(n), rows=rows, n_blocks=n_blocks)


def host_block_set(n_blocks: int, n_shards: int, shard_idx: int) -> range:
    """Disjoint contiguous block range for one host of a row-sharded
    mesh: the 2-D ``"rows"`` staging form generalized to block sets.
    Every block belongs to exactly one shard; shards differ in size by at
    most one block. Block keys already carry ``host_signature()``, so two
    hosts' block sets can never collide in the cache."""
    if not 0 <= shard_idx < n_shards:
        raise ValueError(f"shard_idx {shard_idx} outside [0, {n_shards})")
    base, extra = divmod(int(n_blocks), int(n_shards))
    start = shard_idx * base + min(shard_idx, extra)
    stop = start + base + (1 if shard_idx < extra else 0)
    return range(start, stop)


def decode_block(blk):
    """Widen a compressed staged block (bf16 / int8 dict forms) back to
    the f32 matrix kernels expect — the same traced decode the
    single-shot staging path uses."""
    from ..parallel.trial_map import _stage_decode

    return _stage_decode(blk)


def pad_rows(blk: np.ndarray, rows: int) -> np.ndarray:
    """Zero-pad a partial tail block up to the uniform block height."""
    short = rows - blk.shape[0]
    if short <= 0:
        return blk
    pad = np.zeros((short,) + blk.shape[1:], blk.dtype)
    return np.concatenate([blk, pad], axis=0)


def array_block_source(
    arr, plan: BlockPlan
) -> Callable[[int], np.ndarray]:
    """Host block fetcher over an in-memory array: slice + zero-pad."""

    def fetch(i: int) -> np.ndarray:
        s = plan.start(i)
        blk = np.asarray(arr[s : s + plan.rows])
        return pad_rows(blk, plan.rows)

    return fetch


class RowBlockStreamer:
    """Double-buffered iterator over staged row blocks.

    ``iter_blocks()`` yields ``(block_id, row_start, device_value)`` in
    ascending block order; call it once per pass over the data (a solver
    makes one pass per iteration). While a pass runs, the in-flight block
    and the prefetched next block each hold an explicit stage-cache ref
    (``acquire``), released as the consumer advances — LRU pressure from
    concurrent tenants evicts only blocks the pass is done with, and a
    repeat pass re-stages (or re-hits) them through the ordinary
    single-flight path.

    ``fetch_host(i)`` produces the host-side block (already padded to
    ``plan.rows``); ``to_device`` uploads it (optionally compressing via
    the CS230_STAGE_DTYPE path first). Both run on the prefetch worker
    thread when double-buffering is on.
    """

    def __init__(
        self,
        base_key: tuple,
        fetch_host: Callable[[int], Any],
        to_device: Callable[[Any], Any],
        plan: BlockPlan,
        *,
        block_ids: Optional[Iterable[int]] = None,
        double_buffer: Optional[bool] = None,
        cache=None,
        row_shape: Optional[Tuple[int, ...]] = None,
    ):
        self._base_key = tuple(base_key)
        self._fetch_host = fetch_host
        self._to_device = to_device
        self.plan = plan
        #: per-row feature shape of the DECODED block (kernel drivers
        #: derive their resident-state geometry from it)
        self.row_shape = tuple(row_shape) if row_shape is not None else None
        self._ids = (
            list(block_ids) if block_ids is not None else list(plan.block_ids())
        )
        self._db = (
            stream_double_buffer() if double_buffer is None else bool(double_buffer)
        )
        self._cache = cache if cache is not None else STAGE_CACHE
        self._stats_lock = threading.Lock()
        self.stats = {
            "passes": 0,
            "blocks": 0,       # blocks yielded (hits + uploads)
            "uploads": 0,      # blocks that paid a tunnel upload
            "bytes": 0,        # bytes uploaded (post-compression)
            "upload_s": 0.0,   # upload wall on the worker (misses only)
            "wait_s": 0.0,     # consumer blocked waiting for a block
        }

    def block_key(self, i: int) -> tuple:
        return self._base_key + (int(i),)

    def block_ids(self) -> List[int]:
        return list(self._ids)

    # ---------------- internals ----------------

    def _acquire(self, i: int):
        """Stage (or hit) block ``i`` with an explicit cache ref held.
        Runs on the prefetch worker when double-buffering is on."""
        key = self.block_key(i)
        made = {}

        def make():
            import jax

            val = self._to_device(self._fetch_host(int(i)))
            # block until the device copy lands so the measured wall is
            # the actual upload, not an async enqueue
            val = jax.block_until_ready(val)
            made["nbytes"] = _tree_nbytes(val)
            return val

        t0 = time.perf_counter()
        val, outcome = self._cache.acquire(key, make)
        wall = time.perf_counter() - t0
        return key, val, outcome, wall, made.get("nbytes", 0)

    def iter_blocks(self) -> Iterator[Tuple[int, int, Any]]:
        """One pass over the block set, in ascending order. Re-invoke for
        each additional pass (stats accumulate across passes)."""
        ids = list(self._ids)
        ex = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="tpuml-stream")
            if self._db and len(ids) > 1
            else None
        )
        pending: "collections.deque" = collections.deque()
        pos = 0
        blocks = uploads = nbytes = 0
        upload_s = wait_s = 0.0

        def submit():
            nonlocal pos
            if pos < len(ids):
                i = ids[pos]
                pos += 1
                fut = ex.submit(self._acquire, i) if ex is not None else None
                pending.append((i, fut))

        try:
            submit()
            while pending:
                # keep exactly one extra block in flight: the worker
                # uploads block i+1 while the caller computes on block i
                submit()
                i, fut = pending.popleft()
                t0 = time.perf_counter()
                if fut is not None:
                    key, val, outcome, up_wall, up_bytes = fut.result()
                else:
                    key, val, outcome, up_wall, up_bytes = self._acquire(i)
                wait_s += time.perf_counter() - t0
                blocks += 1
                if outcome != "hit":
                    uploads += 1
                    nbytes += up_bytes
                    upload_s += up_wall
                counter_inc("tpuml_stream_blocks_total")
                try:
                    yield i, self.plan.start(i), val
                finally:
                    # the consumer advanced: this block is evictable again
                    self._cache.release(key)
        finally:
            # abandoned pass / worker error: drop refs the prefetcher took
            while pending:
                _, fut = pending.popleft()
                if fut is None:
                    continue
                try:
                    key = fut.result()[0]
                except BaseException:  # noqa: BLE001 — maker failed: no ref
                    continue
                self._cache.release(key)
            if ex is not None:
                ex.shutdown(wait=True)
            self._finish_pass(blocks, uploads, nbytes, upload_s, wait_s)

    def _finish_pass(self, blocks, uploads, nbytes, upload_s, wait_s):
        if blocks == 0:
            return
        with self._stats_lock:
            self.stats["passes"] += 1
            self.stats["blocks"] += blocks
            self.stats["uploads"] += uploads
            self.stats["bytes"] += nbytes
            self.stats["upload_s"] += upload_s
            self.stats["wait_s"] += wait_s
        hidden_s = max(upload_s - wait_s, 0.0)
        counter_inc("tpuml_stream_passes_total")
        if nbytes:
            counter_inc("tpuml_stream_bytes_total", float(nbytes))
        if upload_s > 0.0:
            counter_inc("tpuml_stream_upload_seconds_total", upload_s)
        if wait_s > 0.0:
            counter_inc("tpuml_stream_wait_seconds_total", wait_s)
        # devprof overlap attribution: the hidden share of the transfer
        # wall lands in the ``stream`` phase; the blocking remainder rides
        # the engine's stage accumulator like any other staging wait
        from ..obs import devprof

        devprof.device_seconds("stream", hidden_s)
        record_event(
            "stage.stream",
            blocks=blocks,
            uploads=uploads,
            nbytes=nbytes,
            upload_s=round(upload_s, 6),
            wait_s=round(wait_s, 6),
            hidden_s=round(hidden_s, 6),
            hidden_frac=(
                round(hidden_s / upload_s, 4) if upload_s > 0.0 else None
            ),
            double_buffer=self._db,
        )

    # ---------------- derived stats ----------------

    def hidden_fraction(self) -> Optional[float]:
        """Share of the cumulative transfer wall hidden behind compute:
        ``1 - wait/upload`` (None until an upload happened)."""
        with self._stats_lock:
            up, wait = self.stats["upload_s"], self.stats["wait_s"]
        if up <= 0.0:
            return None
        return max(0.0, 1.0 - wait / up)


class CsvBlockSource:
    """Sequential, rewindable host block source over chunked CSV ingest.

    ``open_blocks()`` must return a fresh iterator of ``(X_chunk, ...)``
    row arrays (any chunk heights — e.g. ``data/preprocess.py::
    iter_design_blocks``); this class re-chunks them to the plan's
    uniform block height. ``fetch(i)`` serves ascending block indices
    within a pass; an index rewind (a new pass) restarts the underlying
    reader, so the full matrix never materializes on the host — the
    resident set is one reader chunk plus one assembled block.
    """

    def __init__(self, open_blocks: Callable[[], Iterable[np.ndarray]], plan: BlockPlan):
        self._open = open_blocks
        self.plan = plan
        self._lock = threading.Lock()
        self._reader: Optional[Iterator[np.ndarray]] = None
        self._next_block = 0
        self._buf: List[np.ndarray] = []
        self._buf_rows = 0

    def _restart(self):
        self._reader = iter(self._open())
        self._next_block = 0
        self._buf = []
        self._buf_rows = 0

    def fetch(self, i: int) -> np.ndarray:
        rows = self.plan.rows
        with self._lock:
            if self._reader is None or i < self._next_block:
                self._restart()
            if i > self._next_block:
                # a skipped-ahead fetch (per-host block sets): discard
                # intervening rows without assembling them into blocks
                for _ in range(self._next_block, i):
                    self._fill(rows)
                    self._drop(rows)
                    self._next_block += 1
            self._fill(rows)
            blk = self._take(rows)
            self._next_block += 1
        return pad_rows(blk, rows)

    def _fill(self, rows: int):
        while self._buf_rows < rows and self._reader is not None:
            try:
                chunk = np.asarray(next(self._reader))
            except StopIteration:
                self._reader = None
                break
            if chunk.shape[0]:
                self._buf.append(chunk)
                self._buf_rows += chunk.shape[0]

    def _take(self, rows: int) -> np.ndarray:
        got: List[np.ndarray] = []
        need = rows
        while need > 0 and self._buf:
            head = self._buf[0]
            if head.shape[0] <= need:
                got.append(head)
                need -= head.shape[0]
                self._buf.pop(0)
            else:
                got.append(head[:need])
                self._buf[0] = head[need:]
                need = 0
        self._buf_rows -= sum(g.shape[0] for g in got)
        if not got:
            return np.zeros((0,), np.float32)
        return np.concatenate(got, axis=0) if len(got) > 1 else got[0]

    def _drop(self, rows: int):
        self._take(rows)
