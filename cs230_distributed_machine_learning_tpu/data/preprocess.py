"""YAML-driven tabular preprocessing pipeline.

Capability parity with the reference's ``preprocess_data``
(``aws-prod/master/dataset_util.py:43-116``) — same op set, same order, same
YAML schema (see ``titanic_preprocess.yaml``):

1. drop_columns            5. drop_duplicates
2. drop_null (all-or)      6. categorical encode: onehot | label | freq
3. impute: mean|median|mode 7. scale: standard over listed columns
4. outliers: clip|iqr       8. target_column moved to last position

The output contract matters downstream: like the reference
(``worker.py:428-429``), training reads the *last column as the target*.
"""

from __future__ import annotations

from typing import Any, Dict

import pandas as pd


def preprocess_dataframe(df: pd.DataFrame, config: Dict[str, Any]) -> pd.DataFrame:
    config = _normalize(config)

    if "drop_columns" in config:
        df = df.drop(columns=config["drop_columns"], errors="ignore")

    if config.get("drop_null", False):
        df = df.dropna()
    else:
        for col, method in config.get("impute", {}).items():
            if col not in df.columns:
                continue
            if method == "mean":
                df[col] = df[col].fillna(df[col].mean())
            elif method == "median":
                df[col] = df[col].fillna(df[col].median())
            elif method == "mode":
                df[col] = df[col].fillna(df[col].mode()[0])

    for col, method in config.get("outliers", {}).items():
        if col not in df.columns:
            continue
        if method == "clip":
            lower, upper = df[col].quantile(0.01), df[col].quantile(0.99)
            df[col] = df[col].clip(lower, upper)
        elif method == "iqr":
            q1, q3 = df[col].quantile(0.25), df[col].quantile(0.75)
            iqr = q3 - q1
            df = df[(df[col] >= q1 - 1.5 * iqr) & (df[col] <= q3 + 1.5 * iqr)]

    if config.get("drop_duplicates", False):
        df = df.drop_duplicates()

    for col, method in config.get("categorical", {}).items():
        if col not in df.columns:
            continue
        if method == "onehot":
            dummies = pd.get_dummies(df[col], prefix=col, drop_first=False)
            df = pd.concat([df.drop(columns=[col]), dummies], axis=1)
        elif method == "label":
            from sklearn.preprocessing import LabelEncoder

            df[col] = LabelEncoder().fit_transform(df[col].astype(str))
        elif method == "freq":
            df[col] = df[col].map(df[col].value_counts(normalize=True))

    scale = config.get("scale", {})
    if scale.get("method") == "standard":
        for col in scale.get("columns", []):
            if col not in df.columns:
                continue
            std = df[col].std()
            df[col] = (df[col] - df[col].mean()) / std if std != 0 else 0

    target = config.get("target_column")
    if target and target in df.columns:
        df[target] = df.pop(target)

    return df


def chunked_column_stats(
    chunks: "Any", columns: "Any" = None
) -> Dict[str, Dict[str, float]]:
    """Single streaming pass over DataFrame chunks -> per-column
    ``{count, mean, std}`` via Chan/Welford parallel merge — the stats
    half of an out-of-core ``scale: standard`` (data/streaming.py): the
    full column never materializes, yet mean/std match the whole-frame
    computation to f32 round-off."""
    import numpy as np

    acc: Dict[str, list] = {}
    for chunk in chunks:
        cols = list(columns) if columns is not None else [
            c for c in chunk.columns
            if np.issubdtype(np.asarray(chunk[c]).dtype, np.number)
        ]
        for c in cols:
            v = np.asarray(chunk[c], np.float64)
            v = v[np.isfinite(v)]
            if v.size == 0:
                continue
            cnt, mean = float(v.size), float(v.mean())
            m2 = float(((v - mean) ** 2).sum())
            if c not in acc:
                acc[c] = [cnt, mean, m2]
            else:
                n0, mu0, m20 = acc[c]
                delta = mean - mu0
                tot = n0 + cnt
                acc[c] = [
                    tot,
                    mu0 + delta * cnt / tot,
                    m20 + m2 + delta * delta * n0 * cnt / tot,
                ]
    return {
        c: {
            "count": n0,
            "mean": mu0,
            "std": (m20 / n0) ** 0.5 if n0 > 0 else 0.0,
        }
        for c, (n0, mu0, m20) in acc.items()
    }


def iter_design_blocks(
    chunks: "Any",
    stats: Dict[str, Dict[str, float]] = None,
    target_column: str = None,
):
    """Second streaming pass: yield standardized float32 feature blocks
    (target column dropped) — the host block source ``CsvBlockSource``
    re-chunks into uniform streamer rows. With ``stats`` from
    :func:`chunked_column_stats`, columns named there are standardized
    ``(x - mean) / std`` (std 0 -> column zeroed, matching
    ``preprocess_dataframe``'s whole-frame scaler)."""
    import numpy as np

    for chunk in chunks:
        df = chunk
        if target_column is not None and target_column in df.columns:
            df = df.drop(columns=[target_column])
        X = np.asarray(df, np.float32)
        if stats:
            for j, c in enumerate(df.columns):
                s = stats.get(c)
                if s is None:
                    continue
                std = s["std"]
                if std != 0:
                    X[:, j] = (X[:, j] - s["mean"]) / std
                else:
                    X[:, j] = 0.0
        yield X


def _normalize(config: Dict[str, Any]) -> Dict[str, Any]:
    """Accept both mapping and list-of-single-key-mapping YAML styles for
    ``categorical``/``impute``/``outliers`` (the reference's demo YAML uses
    the list style for ``categorical``, titanic_preprocess.yaml:19-22)."""
    out = dict(config)
    for key in ("categorical", "impute", "outliers"):
        val = out.get(key)
        if isinstance(val, list):
            merged: Dict[str, Any] = {}
            for item in val:
                if isinstance(item, dict):
                    merged.update(item)
            out[key] = merged
    return out
