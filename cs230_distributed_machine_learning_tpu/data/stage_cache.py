"""Multi-tenant staged-dataset cache: one device copy per (dataset, device).

The per-TrialData staging cache in parallel/trial_map keyed device copies
on the TrialData *object*, so N concurrent jobs that each resolved their
own TrialData over the same public dataset re-staged it N times — N x the
~3.4 s upload the r5 cold-start breakdown measured, for bytes already
sitting in HBM (ROADMAP item 5; PAPER.md §2's task-farm shape makes the
same-dataset fan-out the common case, not the corner).

This module is the process-global replacement:

- **content-fingerprint keys**: every staged entry is keyed by a sha1 over
  the dataset's actual bytes + shape/dtype + ``n_classes`` + an optional
  ``preprocess_salt`` attribute, plus the default device identity and the
  caller's entry subkey (placement, staging dtype, prepared-form salt).
  Two TrialData objects with identical content share one device copy; a
  dtype or preprocessing difference can never collide. Beyond raw
  dataset tensors, the same keying carries *solver precomputes*: the
  packed LogReg path stages its padded bf16 design matrix and its
  per-(dataset, fold-signature) Lipschitz bound here
  (``models/logistic.py::batched_staged_extras`` via the trial engine's
  ``batched_extra`` subkeys), so repeat dispatches hit instead of
  recomputing.
- **single-flight staging**: concurrent misses on one key perform exactly
  ONE upload — later arrivals wait on the maker's event and reuse its
  entry. ``stats()["uploads"]`` is the observable the concurrency
  benchmark (benchmarks/staging_concurrency.py) and its fast test pin.
- **mesh-shaped entries** (the elastic trial fabric,
  docs/ARCHITECTURE.md "Elastic trial fabric"): a multi-device mesh job
  stages the dataset through the slow host->device tunnel ONCE per
  (dataset, host) — the plain single-device entry, shared with
  single-device jobs — and then builds its mesh-placed form (trial-axis
  replicated or data-axis row-sharded) with an on-device
  ``jax.device_put`` broadcast/reshard that moves bytes over ICI, never
  back through the tunnel. Mesh entries carry the mesh axis spec in
  their subkey so the 1-D replicated and 2-D sharded forms coexist;
  they are cached with ``transport="ici"``, which counts
  ``replications``/``ici_bytes`` instead of tunnel ``uploads`` —
  ``uploads_by_key()`` therefore keeps meaning *tunnel* uploads, the
  <=1-per-(dataset, host) observable the mesh tests pin.
- **refcounted LRU under a device-memory budget**: runs pin the entries
  they touch (``pin_begin``/``pin_end``, wired through
  ``trial_map.run_trials``); eviction walks LRU order, skips pinned
  entries, and stops at ``CS230_STAGE_CACHE_MB`` (default: 40% of the
  device's reported memory limit).
- **observability**: ``tpuml_stage_cache_{hits,misses,uploads,evictions}
  _total`` counters + ``tpuml_stage_cache_{bytes,entries}`` gauges
  (docs/OBSERVABILITY.md), and ``stage.upload`` / ``stage.evict``
  flight-recorder events.

``CS230_STAGE_CACHE=0`` disables the module entirely and restores the
legacy per-TrialData staging path bit-for-bit (parity-pinned in
tests/test_stage_cache.py).
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import counter_inc, gauge_set, record_event
from ..utils.logging import get_logger

logger = get_logger("tpuml.stagecache")


def enabled() -> bool:
    """CS230_STAGE_CACHE=0 restores the legacy per-TrialData staging
    cache (the parity valve). Read per call so tests can flip it live."""
    return os.environ.get("CS230_STAGE_CACHE", "1") != "0"


def strict_enabled() -> bool:
    """CS230_STAGE_STRICT=1 turns the stage budget from advisory into a
    hard ceiling: a single tunnel upload larger than ``budget_bytes()``
    raises :class:`StageBudgetExceeded` instead of staging anyway. On a
    real device that oversize ``device_put`` is an HBM OOM; the strict
    valve reproduces the failure deterministically on CPU, which is how
    the streaming OOM-repro benchmark/tests pin "legacy staging fails
    where CS230_STREAM completes" (benchmarks/streaming_micro.py)."""
    return os.environ.get("CS230_STAGE_STRICT", "0") == "1"


class StageBudgetExceeded(RuntimeError):
    """A single staged entry exceeds the stage-cache budget under
    ``CS230_STAGE_STRICT=1`` — the CPU-deterministic stand-in for the
    device OOM the same upload would hit on real hardware."""


def budget_bytes() -> int:
    """Device-memory budget for staged entries. ``CS230_STAGE_CACHE_MB``
    pins it; the default is 40% of the device's reported bytes_limit
    (backends without memory_stats fall back to the same 8 GB assumption
    the trial engine's chunk planner uses)."""
    env = os.environ.get("CS230_STAGE_CACHE_MB")
    if env:
        try:
            return max(int(float(env) * 1e6), 1)
        except ValueError:
            pass
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(0.4 * stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — no backend / no stats: fallback
        pass
    return int(0.4 * 8e9)


def dataset_fingerprint(data) -> str:
    """Content fingerprint of a TrialData: sha1 over the dataset bytes,
    shape/dtype signature, n_classes, and the optional ``preprocess_salt``
    attribute (preprocessing pipelines that rewrite bytes already move the
    hash; the salt covers semantic changes that do not — e.g. a label
    re-encode producing identical bytes by coincidence). Cached on the
    TrialData object: the hash walks every byte once (~0.1 s for the 25 MB
    covertype matrix), which is noise next to one staging upload but not
    next to a cache hit."""
    fp = getattr(data, "_content_fp", None)
    if fp is not None:
        return fp
    import numpy as np

    h = hashlib.sha1()
    X = data.X
    leaves = (
        [X[k] for k in sorted(X)] if isinstance(X, dict) else [X]
    )
    for leaf in leaves:
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    y = np.ascontiguousarray(np.asarray(data.y))
    h.update(
        repr((y.shape, str(y.dtype), int(getattr(data, "n_classes", 0)))).encode()
    )
    h.update(y.tobytes())
    h.update(str(getattr(data, "preprocess_salt", "")).encode())
    fp = h.hexdigest()
    try:
        object.__setattr__(data, "_content_fp", fp)
    except Exception:  # noqa: BLE001 — exotic TrialData subclass: recompute
        pass
    return fp


def host_signature() -> tuple:
    """Host identity for mesh-shaped cache keys: the "once per host" half
    of the mesh staging contract. Keyed by (platform, process index) —
    every process of a multi-host SPMD slice stages its own local copy,
    but all devices OF one host share it."""
    try:
        import jax

        return (str(jax.devices()[0].platform), int(jax.process_index()))
    except Exception:  # noqa: BLE001 — no backend yet
        return ("none", 0)


def _tree_nbytes(value: Any) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(value):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


class _Entry:
    __slots__ = ("value", "nbytes", "refs")

    def __init__(self, value: Any, nbytes: int):
        self.value = value
        self.nbytes = nbytes
        #: live pins from in-flight runs — never evicted while > 0
        self.refs = 0


class StagedDatasetCache:
    """Process-global refcounted LRU of device-resident staged tensors.

    Keys are opaque tuples built by the trial engine:
    ``(dataset_fingerprint, device_signature, *entry_subkey)``. Values are
    whatever the staging ``make()`` returned (device arrays / pytrees).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Any, _Entry]" = (
            collections.OrderedDict()
        )
        #: key -> Event for a staging upload currently in flight
        self._inflight: Dict[Any, threading.Event] = {}
        self._bytes = 0
        self._local = threading.local()
        self._stats = {
            "hits": 0,
            "misses": 0,
            "uploads": 0,
            "evictions": 0,
            "unevictable_overflows": 0,
            # ---- mesh fabric accounting (transport="ici" entries) ----
            #: on-device broadcast/reshard builds of mesh-shaped entries
            "replications": 0,
            #: bytes that crossed the slow host->device tunnel (misses of
            #: transport="tunnel" entries)
            "tunnel_bytes": 0,
            #: bytes moved device-to-device (ICI on TPU meshes) building
            #: mesh-shaped entries
            "ici_bytes": 0,
        }
        #: per-key upload counts — the concurrency benchmark's observable
        self._uploads_by_key: collections.Counter = collections.Counter()

    # ---------------- pin scopes (refcounting) ----------------
    #
    # A run (trial_map.run_trials) opens a pin scope; every entry it
    # touches gains one ref for the scope's lifetime, so eviction under
    # memory pressure can never drop a tensor out from under an in-flight
    # dispatch. Scopes are per-thread and nest (coordinator job threads
    # and cluster workers each run their own).

    def pin_begin(self) -> int:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(set())
        return len(stack)

    def pin_end(self, token: int) -> None:
        stack = getattr(self._local, "stack", None)
        if not stack:
            return
        pinned = stack.pop()
        with self._lock:
            for key in pinned:
                entry = self._entries.get(key)
                if entry is not None:
                    entry.refs = max(0, entry.refs - 1)

    def _pin_locked(self, key: Any) -> None:
        stack = getattr(self._local, "stack", None)
        if not stack:
            return
        scope = stack[-1]
        if key not in scope:
            scope.add(key)
            entry = self._entries.get(key)
            if entry is not None:
                entry.refs += 1

    # ---------------- explicit refs (cross-thread pins) ----------------
    #
    # Pin scopes are thread-local, which is right for a run's own thread
    # but useless for the streaming prefetch worker: it stages block i+1
    # on a different thread than the one consuming block i. acquire()
    # therefore takes an explicit ref on the staged entry that release()
    # drops from ANY thread — the streamer holds one per in-flight or
    # prefetched block so LRU pressure can never evict them mid-pass.

    def acquire(
        self, key: Any, make: Callable[[], Any], *,
        transport: str = "tunnel", ici_bytes: Optional[int] = None,
    ) -> Tuple[Any, str]:
        """``get_or_stage`` plus one explicit ref on the entry. The loop
        closes the stage->pin race: if the entry was evicted between the
        stage returning and the ref landing (another tenant's burst), we
        simply re-stage — the ref is only ever taken on a live entry
        holding the value we are about to hand out."""
        while True:
            value, outcome = self.get_or_stage(
                key, make, transport=transport, ici_bytes=ici_bytes
            )
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None and entry.value is value:
                    entry.refs += 1
                    return value, outcome

    def release(self, key: Any) -> None:
        """Drop one explicit ref taken by :meth:`acquire`."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.refs = max(0, entry.refs - 1)

    # ---------------- lookup / staging ----------------

    def get_or_stage(
        self, key: Any, make: Callable[[], Any], *,
        transport: str = "tunnel", ici_bytes: Optional[int] = None,
    ) -> Tuple[Any, str]:
        """Return ``(value, outcome)`` where outcome is ``"hit"`` (cached),
        ``"wait"`` (another thread staged it while we waited — no upload
        paid by THIS caller beyond the wait), or ``"miss"`` (this caller
        performed the upload). Exactly one concurrent caller per key runs
        ``make()``; a failed make releases the waiters to retry (the next
        one becomes the maker).

        ``transport`` attributes the miss's bytes: ``"tunnel"`` (default)
        is a host->device staging upload and counts toward ``uploads`` /
        ``tunnel_bytes``; ``"ici"`` is an on-device broadcast/reshard of
        an already-resident tensor (mesh-shaped entries) and counts
        toward ``replications`` / ``ici_bytes`` instead — *never* toward
        the tunnel upload counters the <=1-per-(dataset, host) contract
        is asserted on. ``ici_bytes`` overrides the traffic estimate for
        an ici miss (e.g. nbytes x (n_devices - 1) for a full replicate);
        default is the made value's footprint."""
        waited = False
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self._stats["hits"] += 1
                    self._pin_locked(key)
                    counter_inc("tpuml_stage_cache_hits_total")
                    return entry.value, ("wait" if waited else "hit")
                ev = self._inflight.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[key] = ev
                    break
            waited = True
            ev.wait()

        t0 = time.perf_counter()
        try:
            value = make()
        except BaseException:
            # release waiters to retry (one becomes the next maker)
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()
            raise
        wall_s = time.perf_counter() - t0
        nbytes = _tree_nbytes(value)
        ici = transport == "ici"
        budget = budget_bytes()
        if not ici and strict_enabled() and nbytes > budget:
            # strict budget: refuse the oversize upload (the CPU stand-in
            # for the HBM OOM it would be on device) and release waiters
            # — they will retry and hit the same ceiling deterministically
            with self._lock:
                self._stats["unevictable_overflows"] += 1
                self._inflight.pop(key, None)
            ev.set()
            del value
            counter_inc("tpuml_stage_cache_overflow_total")
            record_event(
                "stage.overflow", key=repr(key), nbytes=nbytes,
                budget_bytes=budget, reason="strict",
            )
            raise StageBudgetExceeded(
                f"staged entry {key!r} is {nbytes / 1e6:.1f} MB but the "
                f"stage budget is {budget / 1e6:.1f} MB "
                "(CS230_STAGE_STRICT=1); stream the dataset instead "
                "(CS230_STREAM, data/streaming.py) or raise "
                "CS230_STAGE_CACHE_MB"
            )
        moved = int(ici_bytes) if (ici and ici_bytes is not None) else nbytes
        evicted: List[Tuple[Any, int]] = []
        overflow = 0
        with self._lock:
            self._entries[key] = _Entry(value, nbytes)
            self._entries.move_to_end(key)
            self._bytes += nbytes
            self._stats["misses"] += 1
            if ici:
                self._stats["replications"] += 1
                self._stats["ici_bytes"] += moved
            else:
                self._stats["uploads"] += 1
                self._stats["tunnel_bytes"] += nbytes
                self._uploads_by_key[key] += 1
            self._pin_locked(key)
            evicted, overflow = self._evict_over_budget_locked(exclude=key)
            total_bytes, n_entries = self._bytes, len(self._entries)
            # entry inserted: waiters must see it BEFORE the event fires,
            # or they would loop back into a duplicate upload
            self._inflight.pop(key, None)
        ev.set()
        counter_inc("tpuml_stage_cache_misses_total")
        if ici:
            counter_inc("tpuml_stage_cache_replications_total")
            counter_inc("tpuml_stage_cache_ici_bytes_total", float(moved))
        else:
            counter_inc("tpuml_stage_cache_uploads_total")
            counter_inc("tpuml_stage_cache_tunnel_bytes_total", float(nbytes))
        gauge_set("tpuml_stage_cache_bytes", float(total_bytes))
        gauge_set("tpuml_stage_cache_entries", float(n_entries))
        record_event(
            "stage.replicate" if ici else "stage.upload",
            key=repr(key), nbytes=nbytes, wall_s=round(wall_s, 6),
            cache_bytes=total_bytes, cache_entries=n_entries,
            **({"ici_bytes": moved} if ici else {}),
        )
        for ekey, enbytes in evicted:
            counter_inc("tpuml_stage_cache_evictions_total")
            record_event("stage.evict", key=repr(ekey), nbytes=enbytes)
        if overflow:
            # every survivor was pinned: the cache is committed beyond
            # its budget. The overflow is forced (live tensors are never
            # dropped) but no longer silent — operators alert on the
            # counter, the flight recorder carries the context.
            counter_inc("tpuml_stage_cache_overflow_total")
            record_event(
                "stage.overflow", key=repr(key), nbytes=nbytes,
                overflow_bytes=overflow, budget_bytes=budget,
                cache_bytes=total_bytes, cache_entries=n_entries,
                reason="pinned",
            )
        return value, "miss"

    def _evict_over_budget_locked(
        self, exclude: Any = None
    ) -> Tuple[List[Tuple[Any, int]], int]:
        """LRU eviction down to the budget, skipping pinned entries and
        the just-inserted key (a single over-budget dataset must stage and
        serve its run, then age out). Returns the evicted (key, nbytes)
        plus the bytes still over budget after eviction (non-zero only
        when every survivor is pinned — the caller emits the overflow
        counter/event outside the lock)."""
        budget = budget_bytes()
        evicted: List[Tuple[Any, int]] = []
        if self._bytes <= budget:
            return evicted, 0
        for key in list(self._entries):
            if self._bytes <= budget:
                break
            entry = self._entries[key]
            if key == exclude or entry.refs > 0:
                continue
            del self._entries[key]
            self._bytes -= entry.nbytes
            self._stats["evictions"] += 1
            evicted.append((key, entry.nbytes))
        overflow = max(self._bytes - budget, 0)
        if overflow:
            # every survivor is pinned (or the newcomer itself): nothing
            # more can go — record the overflow, never drop live tensors
            self._stats["unevictable_overflows"] += 1
        if evicted:
            logger.info(
                "Staged-dataset cache evicted %d entries (%.1f MB) to fit "
                "the %.0f MB budget",
                len(evicted), sum(nb for _, nb in evicted) / 1e6,
                budget / 1e6,
            )
        return evicted, overflow

    # ---------------- introspection / tests ----------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
            out["pinned"] = sum(
                1 for e in self._entries.values() if e.refs > 0
            )
            return out

    def uploads_by_key(self) -> Dict[Any, int]:
        """Per-key upload counts since process start (or ``clear()``) —
        the exactly-one-upload-per-(dataset, device) observable."""
        with self._lock:
            return dict(self._uploads_by_key)

    def contains(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def keys(self) -> List[Any]:
        with self._lock:
            return list(self._entries)

    def clear(self) -> None:
        """Drop every entry and reset counters (tests)."""
        with self._lock:
            self._entries.clear()
            self._uploads_by_key.clear()
            self._bytes = 0
            for k in self._stats:
                self._stats[k] = 0
        gauge_set("tpuml_stage_cache_bytes", 0.0)
        gauge_set("tpuml_stage_cache_entries", 0.0)


#: the process-global cache instance every executor/run shares
STAGE_CACHE = StagedDatasetCache()
