"""LogisticRegression kernel: multinomial softmax regression, TPU-first.

Capability target: the reference's `LogisticRegression` trials
(``aws-prod/worker/worker.py:43``) — sklearn's L2-penalized logistic
regression (lbfgs solver), scored by accuracy and 5-fold CV. Instead of
per-trial CPU fits, this kernel is pure-functional and vmappable: one
compiled executable fits *all* trials in a bucket, with ``C``/``max_iter``/
``tol`` traced per-trial scalars.

Objective (matching sklearn): ``0.5 * ||W_coef||_F^2 + C * sum_i w_i *
xent_i`` with the intercept unpenalized. Two solvers, chosen at bucket-build
time from data shape (see ``resolve_static``):

- **newton**: exact full-Hessian Newton steps (quadratic convergence; the
  Hessian build is two MXU matmuls). Used when ``(d+1)*n_classes`` and the
  per-sample workspace are small. Converges to the same optimum as sklearn's
  lbfgs, so scores — and therefore ``best_params_`` — agree to tolerance.
- **nesterov**: accelerated full-batch gradient descent with a
  power-iteration Lipschitz step size, for large ``n*d*c`` (e.g. Covertype).
  Per-iteration cost is one [n,d]x[d,c] matmul — ideal MXU shape.

For binary problems sklearn fits a single logit; a 2-column softmax with the
penalty doubled has the same optimum predictive distribution (the penalty on
the logit difference matches), so we always use the softmax form and scale
the penalty by 2 when ``n_classes == 2``.

Known limitation: iteration counts are compile-time caps (``_NEWTON_STEPS``,
``_NESTEROV_STEPS``) because scan lengths are static; a per-trial
``max_iter`` below the cap is honored via masking, but one above it is
truncated. Newton's quadratic convergence makes 25 steps ample in practice;
the Nesterov path may under-converge vs sklearn lbfgs on hard problems —
revisit with an L-BFGS kernel if score-parity tests show drift.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from .base import ModelKernel, add_intercept

_NEWTON_STEPS = 25
_NESTEROV_STEPS = 400
# newton only when the flattened Hessian dim and the [n, dp*c] workspace fit
_NEWTON_MAX_DIM = 512
_NEWTON_MAX_WORKSPACE = 4_000_000


class LogisticRegressionKernel(ModelKernel):
    name = "LogisticRegression"
    task = "classification"
    hyper_defaults = {"C": 1.0, "max_iter": 100.0, "tol": 1e-4}
    static_defaults = {"fit_intercept": True, "penalty": "l2"}

    def trace_salt(self):
        """CS230_MASKED_GRAD selects the masked-gradient formulation and
        CS230_FUSED_STEP the packed scan body at trace time (see
        ``_masked_grad_mode`` / ``_fused_step_mode``) — both must key
        every executable cache like the tree histogram knobs do. The salt
        carries the RESOLVED modes, not the raw strings: invalid/alias
        values collapse to the same behavior and must share a cache key.
        CS230_STREAM joins them (resolved off/auto/force): the streamed
        and single-shot drivers stage different dataset forms, so every
        executable/prepared cache must re-key when the valve moves.
        CS230_CURVES joins too: with capture on, the solver scans carry
        a trace buffer and emit extra outputs, so flipping the valve (or
        CS230_CURVE_POINTS) must re-key every executable cache."""
        from ..data.streaming import stream_mode
        from ..obs.curves import curves_salt

        return (_masked_grad_mode(), _fused_step_mode(), stream_mode(),
                curves_salt())

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if static.get("penalty") not in ("l2", None, "none"):
            raise ValueError(
                f"LogisticRegression penalty={static.get('penalty')!r} not supported"
            )
        c = max(int(n_classes), 2)
        dp = d + (1 if static.get("fit_intercept", True) else 0)
        method = (
            "newton"
            if dp * c <= _NEWTON_MAX_DIM and n * dp * c <= _NEWTON_MAX_WORKSPACE
            else "nesterov"
        )
        return {**static, "_method": method}

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        return self._fit(X, y, w, hyper, static, trace=False)[0]

    def fit_curve(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        """Capture hook (docs/OBSERVABILITY.md "Trial telemetry plane"):
        same fit, plus a bounded grad-norm trace written from inside the
        solver scan — one f32 sample per ``stride`` iterations, at most
        ``CS230_CURVE_POINTS`` slots. Returns ``(params, curve)`` with
        ``curve = {"gmax": [P'], "stride": scalar, "steps": scalar}``."""
        return self._fit(X, y, w, hyper, static, trace=True)

    def _fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any],
             trace: bool):
        n_classes = int(static["_n_classes"])
        c = max(n_classes, 2)
        fit_intercept = bool(static.get("fit_intercept", True))
        use_penalty = static.get("penalty") in ("l2",)

        A = add_intercept(X, fit_intercept)
        dp = A.shape[1]
        Y = jax.nn.one_hot(y, c, dtype=jnp.float32)
        w = w.astype(jnp.float32)

        C = jnp.asarray(hyper["C"], jnp.float32)
        max_iter = jnp.asarray(hyper["max_iter"], jnp.float32)
        tol = jnp.asarray(hyper["tol"], jnp.float32)

        lam = jnp.where(use_penalty, 1.0, 0.0) * (2.0 if n_classes == 2 else 1.0)
        # intercept row is unpenalized (sklearn semantics)
        pen_mask = jnp.ones((dp, c), jnp.float32)
        if fit_intercept:
            pen_mask = pen_mask.at[-1, :].set(0.0)

        W0 = jnp.zeros((dp, c), jnp.float32)

        # large-n path: bf16 matmul inputs with f32 accumulation — the MXU's
        # native mode, ~4x the f32 throughput; Newton path stays f32 (its
        # Hessian solve is precision-sensitive and small anyway)
        if static["_method"] == "nesterov":
            def mm(a, b):
                return jnp.matmul(
                    a.astype(jnp.bfloat16),
                    b.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
        else:
            mm = jnp.matmul

        mode = _masked_grad_mode()
        if static["_method"] == "newton":
            steps = int(static.get("_iters", _NEWTON_STEPS))
            W, tr = _newton(A, Y, w, W0, mm, C, lam, pen_mask, max_iter, tol,
                            steps, fused=(mode != "legacy"), trace=trace)
        else:
            steps = int(static.get("_iters", _NESTEROV_STEPS))
            grad_fn = _make_masked_grad_fn(A, Y, y, w, C, lam, pen_mask, mm, mode)
            W, tr = _nesterov(A, w, W0, grad_fn, C, lam, max_iter, tol, steps,
                              trace=trace)
        if not trace:
            return W, None
        from ..obs.curves import trace_stride

        stride = trace_stride(steps)
        return W, {
            "gmax": tr,
            "stride": jnp.asarray(float(stride), jnp.float32),
            "steps": jnp.asarray(float(steps), jnp.float32),
        }

    def bucket_static(self, static: Dict[str, Any], hypers) -> Dict[str, Any]:
        """Engine hook: with the bucket's hyper values known, cap the static
        scan length at the largest per-trial max_iter so masked-out
        iterations aren't executed at all."""
        cap = _NEWTON_STEPS if static["_method"] == "newton" else _NESTEROV_STEPS
        max_iters = [int(h.get("max_iter", 100)) for h in hypers] or [cap]
        return {**static, "_iters": max(1, min(cap, max(max_iters)))}

    # ---- out-of-core row-block streaming (data/streaming.py) ----

    def stream_applicable(self, static: Dict[str, Any], n: int, d: int) -> bool:
        """Only the Nesterov driver accumulates across row blocks: its
        gradient and power-iteration are row sums. Newton's Hessian
        solve wants the whole workspace resident — and its n-threshold
        (``_NEWTON_MAX_WORKSPACE``) keeps it under any realistic stage
        budget anyway."""
        return static.get("_method") == "nesterov"

    def stream_form(self, X_np, static: Dict[str, Any]):
        """Engine hook: the row-major host array blocks are sliced from,
        plus a salt naming the form in block cache keys."""
        return np.asarray(X_np, np.float32), ("raw", "f32")

    def stream_scores(self, streamer, y_pad, TW, EW, hyper_batch, static, n):
        """Block-accumulated Nesterov over a RowBlockStreamer: one pass
        per solver iteration (plus 31 Lipschitz passes and one eval
        pass), partial gradients summed across blocks — the ``fit`` +
        ``weighted_accuracy`` composition restructured so no array of
        ``n`` rows is ever device-resident. Pad rows carry zero sample
        weight, so every block-sum matches the single-shot value up to
        f32 summation order (the parity tests/test_streaming.py pins)."""
        return _stream_nesterov_scores(
            streamer, y_pad, TW, EW, hyper_batch, static, n
        )

    def predict(self, params, X, static: Dict[str, Any]):
        fit_intercept = bool(static.get("fit_intercept", True))
        A = add_intercept(X, fit_intercept)
        return jnp.argmax(A @ params, axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static: Dict[str, Any]):
        """Binary decision margin = logit(class 1) - logit(class 0) (the
        2-column softmax's logit difference equals sklearn's single-logit
        decision_function up to solver tolerance)."""
        fit_intercept = bool(static.get("fit_intercept", True))
        A = add_intercept(X, fit_intercept)
        Z = A @ params
        return Z[:, 1] - Z[:, 0]

    def predict_proba(self, params, X, static: Dict[str, Any]):
        """Softmax class probabilities (sklearn's multinomial
        predict_proba up to solver tolerance)."""
        fit_intercept = bool(static.get("fit_intercept", True))
        A = add_intercept(X, fit_intercept)
        return jax.nn.softmax(A @ params, axis=-1)

    def memory_estimate_mb(self, n, d, static):
        # marginal per-(trial,split) working set: a few [n, c] activation/
        # gradient buffers (the [n, d] design matrix is shared, not vmapped)
        c = max(int(static.get("_n_classes", 2)), 2)
        return max(1.0, 3.0 * 4.0 * n * c / 1e6)

    def macs_estimate(self, n, d, static):
        """Analytical per-(trial, split) cost — lets the engine route
        sub-accelerator-scale buckets to host execution."""
        c = max(int(static.get("_n_classes", 2)), 2)
        newton = static.get("_method") == "newton"
        steps = int(
            static.get("_iters", _NEWTON_STEPS if newton else _NESTEROV_STEPS)
        )
        per_iter = 3.0 * n * (d + 1) * c
        if newton:
            dim = (d + 1) * c
            per_iter += n * dim * (d + 1) + float(dim) ** 3
        return steps * per_iter

    # ---- fused Pallas batched path (ops/pallas_logreg.py) ----------------
    #
    # On TPU, large-n nesterov buckets bypass the generic vmap engine: all
    # trials' weights are packed class-major into one matrix and the whole
    # fit (gradient scan) + eval runs as ONE jitted call per chunk. The
    # probabilities tensor never touches HBM and each dispatch amortizes the
    # host round-trip (measured ~7x per-iteration over the vmap path on
    # v5e for the Covertype north-star config).

    #: trials per packed weight block; engine rounds chunks to this multiple
    batched_trial_multiple = 128
    batched_chunk_cap = 1024

    def batched_applicable(self, static: Dict[str, Any], n: int, d: int) -> bool:
        if static.get("_method") != "nesterov":
            return False
        dpp = _ceil_to(d + 2, 64)  # + intercept, rounded
        if dpp > 512:  # W block would blow the VMEM budget
            return False
        if _interpret_mode():
            return True
        return jax.default_backend() == "tpu" and n >= 4096

    def batched_staged_extras(self, static, n, d, n_classes, n_splits,
                              fold_signature=None):
        """Dispatch-invariant device inputs of the packed path, staged by
        the trial engine in the multi-tenant stage cache
        (data/stage_cache.py) and merged into the dispatch ``hyper`` dict
        under the returned names:

        - ``_logreg_ab``: the padded bf16 design matrix — every dispatch
          after the first stops re-padding and re-casting the full A
          inside the jit (and repeat jobs over a cached dataset pay
          nothing at all).
        - ``_logreg_lam_max``: the per-split Lipschitz power iteration
          (30 matmul round-trips over A), which depends only on (dataset,
          fold weights) — keyed by the fold-plan signature so every chunk
          dispatch after the first is a cache hit.

        Returns ``{name: (subkey | None, make)}``; ``make(ctx)`` receives
        ``{"X", "y", "TW", "EW", "decode"}`` device args. A ``None``
        subkey means compute once per bucket, don't cache (no fold
        signature to key on). Empty in ``legacy`` mode: the rollback path
        must keep deriving everything inline, bit-for-bit."""
        if _fused_step_mode() == "legacy":
            return {}
        if not self.batched_applicable(static, n, d):
            return {}
        geo = _packed_geometry(static, n, d, n_classes, n_splits)
        fit_intercept, dp = geo["fit_intercept"], geo["dp"]
        dpp, n_pad = geo["dpp"], geo["n_pad"]

        def pad_a(X):
            A = add_intercept(X, fit_intercept)
            return jnp.pad(A, ((0, n_pad - n), (0, dpp - dp)))

        def make_ab(ctx):
            f = jax.jit(
                lambda X: pad_a(ctx["decode"](X)).astype(jnp.bfloat16)
            )
            return f(ctx["X"])

        def make_lam_max(ctx):
            def compute(X, TW):
                A = pad_a(ctx["decode"](X))
                TWp = jnp.pad(
                    TW.astype(jnp.float32), ((0, 0), (0, n_pad - n))
                )
                return _packed_lam_max(A, TWp)

            return jax.jit(compute)(ctx["X"], ctx["TW"])

        return {
            "_logreg_ab": (("ab", fit_intercept, dpp, n_pad), make_ab),
            "_logreg_lam_max": (
                None
                if fold_signature is None
                else ("lam_max", fold_signature, fit_intercept, dpp, n_pad),
                make_lam_max,
            ),
        }

    def build_batched_fn(self, static, n, d, n_classes, n_splits, chunk):
        """Returns fn(X, y, TW, EW, hyper) -> {"score": [chunk, n_splits]}
        (same contract as the engine's vmapped executable), or None when the
        packed path doesn't apply. One call = full fit scan + eval.

        ``hyper`` may carry the staged forms from
        ``batched_staged_extras`` (the engine merges them in); when absent
        — direct calls, benchmarks, ``legacy`` mode — everything is
        derived inline, bit-identically."""
        if not self.batched_applicable(static, n, d):
            return None
        Tw = self.batched_trial_multiple
        if chunk % Tw:
            return None

        from ..ops.pallas_logreg import (
            fused_step_applicable,
            packed_nesterov_step,
            packed_softmax_grad,
        )

        interpret = _interpret_mode()
        geo = _packed_geometry(static, n, d, n_classes, n_splits)
        c, S = geo["c"], geo["S"]
        fit_intercept = geo["fit_intercept"]
        lam = geo["lam"]
        steps = int(static.get("_iters", _NESTEROV_STEPS))
        n_wb = chunk // Tw
        Bblk = S * Tw
        NB = c * Bblk
        dp, dpp = geo["dp"], geo["dpp"]
        bm = 256
        rc = geo["rc"]  # eval row-chunk
        n_pad = geo["n_pad"]  # multiple of rc (and of bm)
        mode = _fused_step_mode()
        # auto routes through the fused step kernel whenever its weight
        # blocks fit the VMEM gate; pallas forces it (tiny test shapes);
        # legacy keeps the pre-fusion scan body as the parity reference
        use_fused = mode == "pallas" or (
            mode == "auto" and fused_step_applicable(dpp, NB, bm)
        )
        from ..obs.curves import curves_enabled, trace_stride

        capture = curves_enabled()
        tr_stride = trace_stride(steps) if capture else 1
        tr_used = -(-steps // tr_stride) if capture else 0

        # static column maps: block col j -> (split, trial-in-block)
        j = np.arange(Bblk)
        split_of = j // Tw
        trial_map = (np.arange(n_wb)[:, None] * Tw + (j % Tw)[None, :]).clip(
            max=chunk - 1
        )
        # rows: penalty applies to real feature rows, never the intercept/pad
        pen_row = np.zeros((1, dpp, 1), np.float32)
        pen_row[0, :dp, 0] = 1.0
        if fit_intercept:
            pen_row[0, dp - 1, 0] = 0.0

        split_of_j = jnp.asarray(split_of)
        trial_map_j = jnp.asarray(trial_map)
        pen_row_j = jnp.asarray(pen_row)

        def fn(X, y, TW, EW, hyper):
            A = None
            if "_logreg_ab" not in hyper or "_logreg_lam_max" not in hyper:
                A = add_intercept(X, fit_intercept)  # [n, dp] f32
                A = jnp.pad(A, ((0, n_pad - n), (0, dpp - dp)))
            Ab = (
                hyper["_logreg_ab"]
                if "_logreg_ab" in hyper
                else A.astype(jnp.bfloat16)
            )
            y_pad = jnp.pad(y.astype(jnp.int32), (0, n_pad - n))
            y2 = y_pad[:, None]
            TWp = jnp.pad(TW.astype(jnp.float32), ((0, 0), (0, n_pad - n)))
            EWp = jnp.pad(EW.astype(jnp.float32), ((0, 0), (0, n_pad - n)))
            WSP = TWp.T  # [n_pad, S]

            Cb = jnp.take(hyper["C"], trial_map_j)  # [n_wb, Bblk]
            maxit_b = jnp.take(hyper["max_iter"], trial_map_j)
            tol_b = jnp.take(hyper["tol"], trial_map_j)

            # Lipschitz bound per split: L <= 0.5*C*lam_max(A' diag(w) A)
            # + lam — precomputed once per (dataset, folds) and staged by
            # batched_staged_extras when available, else inline
            lam_max_s = (
                hyper["_logreg_lam_max"]
                if "_logreg_lam_max" in hyper
                else _packed_lam_max(A, TWp)
            )  # [S]
            lam_s = lam_max_s[split_of_j]  # [Bblk]
            step_b = 1.0 / (0.5 * Cb * lam_s[None, :] + lam + 1e-6)

            W0 = jnp.zeros((n_wb, dpp, NB), jnp.float32)
            done0 = jnp.zeros((n_wb, Bblk), bool)

            # fixed-length scan (length already capped to the bucket's max
            # max_iter by bucket_static's _iters). A while_loop with an
            # all-converged early exit measures ~20% SLOWER here: the
            # per-step cond reduce acts as a barrier, and slow-converging
            # trials run to max_iter anyway.
            tr0 = (
                jnp.zeros((tr_used, n_wb, Bblk), jnp.float32)
                if capture else None
            )

            if use_fused:
                pen_col = pen_row_j[0]  # [dpp, 1]

                def body(carry, t):
                    W, Wp, done, tr = carry
                    W, Wp, gmax = packed_nesterov_step(
                        Ab, W, Wp, y2, WSP, t, done.astype(jnp.float32),
                        step_b, Cb, maxit_b, pen_col,
                        c=c, S=S, Tw=Tw, bm=bm, lam=lam,
                        interpret=interpret,
                    )
                    done = jnp.logical_or(done, gmax < tol_b)
                    if capture:
                        tr = tr.at[jnp.asarray(t, jnp.int32) // tr_stride].set(gmax)
                    return (W, Wp, done, tr), None

            else:
                step_full = jnp.tile(step_b, (1, c))[:, None, :]  # [n_wb,1,NB]
                Cb_full = jnp.tile(Cb, (1, c))[:, None, :]

                def body(carry, t):  # legacy scan body — parity reference
                    W, Wp, done, tr = carry
                    mom = t / (t + 3.0)
                    V = W + mom * (W - Wp)
                    Graw = packed_softmax_grad(
                        Ab, V.astype(jnp.bfloat16), y2, WSP,
                        c=c, S=S, Tw=Tw, bm=bm, interpret=interpret,
                    )
                    G = Cb_full * Graw + lam * pen_row_j * V
                    gmax = jnp.max(
                        jnp.abs(G).reshape(n_wb, dpp, c, Bblk), axis=(1, 2)
                    )  # [n_wb, Bblk]
                    active = jnp.logical_and(
                        t < maxit_b, jnp.logical_not(done)
                    )
                    act = jnp.tile(active, (1, c))[:, None, :]
                    W_new = jnp.where(act, V - step_full * G, W)
                    Wp_new = jnp.where(act, W, Wp)
                    done = jnp.logical_or(done, gmax < tol_b)
                    if capture:
                        tr = tr.at[jnp.asarray(t, jnp.int32) // tr_stride].set(gmax)
                    return (W_new, Wp_new, done, tr), None

            (W, _, _, tr_out), _ = jax.lax.scan(
                body, (W0, W0, done0, tr0), jnp.arange(steps, dtype=jnp.float32)
            )

            # ---- eval: streamed row chunks, argmax over the class axis ----
            # (f32: eval runs once per dispatch, and argmax ties near fold
            # boundaries are where bf16 noise could flip best_params_)
            def eval_body(acc, start):
                a = jax.lax.dynamic_slice(Ab, (start, 0), (rc, dpp)).astype(
                    jnp.float32
                )
                logits = jnp.einsum(
                    "rd,wdn->wrn", a, W, preferred_element_type=jnp.float32
                )
                pred = jnp.argmax(logits.reshape(n_wb, rc, c, Bblk), axis=2)
                yc = jax.lax.dynamic_slice(y_pad, (start,), (rc,))
                # slice the [S, n_pad] fold weights first, then expand to
                # trials: keeps the loop-invariant at [S, n_pad] instead of
                # materializing a [Bblk, n_pad] gather (~S*Tw/S x larger —
                # ~1.8 GB on the Covertype north-star config)
                wev = jax.lax.dynamic_slice(
                    EWp, (0, start), (S, rc)
                )[split_of_j].T  # [rc, Bblk]
                hit = (pred == yc[None, :, None]).astype(jnp.float32)
                acc = acc + jnp.sum(hit * wev[None], axis=1)
                return acc, None

            acc0 = jnp.zeros((n_wb, Bblk), jnp.float32)
            acc, _ = jax.lax.scan(
                eval_body, acc0, jnp.arange(0, n_pad, rc, dtype=jnp.int32)
            )
            den = jnp.maximum(jnp.sum(EW.astype(jnp.float32), axis=1), 1e-12)  # [S]
            score_b = acc / den[split_of_j][None, :]
            score = score_b.reshape(n_wb, S, Tw).transpose(0, 2, 1).reshape(chunk, S)
            out = {"score": score}
            if capture:
                # same lane->(trial, split) mapping as score, with the
                # trace-slot axis carried along as a trailing dim
                curve = (
                    tr_out.transpose(1, 2, 0)
                    .reshape(n_wb, S, Tw, tr_used)
                    .transpose(0, 2, 1, 3)
                    .reshape(chunk, S, tr_used)
                )
                out["curve_gmax"] = curve
                out["curve_stride"] = jnp.full(
                    (chunk, S), float(tr_stride), jnp.float32
                )
                out["curve_steps"] = jnp.full(
                    (chunk, S), float(steps), jnp.float32
                )
            return out

        return fn


def _ceil_to(x: int, m: int) -> int:
    from ..parallel.mesh import pad_to_multiple

    return pad_to_multiple(x, m)


def _interpret_mode() -> bool:
    """CS230_PALLAS_INTERPRET=1 forces the packed path with the interpreter
    (CPU test coverage for the TPU kernel)."""
    return os.environ.get("CS230_PALLAS_INTERPRET", "") == "1"


def _masked_grad_mode() -> str:
    """Valve for the fused masked-gradient formulation (ISSUE 6 tentpole).

    - ``auto`` (default): fused-mask XLA formulation everywhere; the fused
      Pallas lane kernel for large-n nesterov fits on a real TPU backend.
    - ``xla``: fused-mask XLA formulation only (never the lane kernel).
    - ``pallas``: force the Pallas lane kernel (uses the interpreter off
      TPU — combine with CS230_PALLAS_INTERPRET=1 in tests). Applies to
      the grad-descent driver only: the ``_newton`` driver needs the
      probabilities for its Hessian anyway, so it always runs the fused
      XLA form (any non-``legacy`` mode).
    - ``legacy``: the pre-fusion formulation (separate ``w*(P-Y)``
      elementwise pass per iteration), kept for A/B and rollback.
    """
    mode = os.environ.get("CS230_MASKED_GRAD", "auto").lower()
    return mode if mode in ("auto", "xla", "pallas", "legacy") else "auto"


def _fused_step_mode() -> str:
    """Valve for the fused packed Nesterov step kernel (ISSUE 10 tentpole).

    - ``auto`` (default): one ``packed_nesterov_step`` Pallas call per
      scan iteration — momentum extrapolation, masked softmax-Gram
      gradient, C/L2 scaling, the ``max|G|`` reduce, and the done-masked
      W/Wp writeback all fused in VMEM with the weights aliased in place
      — whenever the packed path runs (TPU, or interpret mode on CPU)
      and the weight blocks pass the VMEM gate
      (``fused_step_applicable``); the legacy body otherwise.
    - ``pallas``: force the fused kernel, bypassing the VMEM gate (tests
      force tiny shapes through it; combine with CS230_PALLAS_INTERPRET=1
      off-TPU).
    - ``legacy``: the pre-fusion scan body (separate XLA elementwise
      passes around ``packed_softmax_grad``), kept as the parity
      reference and rollback — it also keeps deriving Ab and the
      Lipschitz bound inline (no staged extras), bit-for-bit the old
      path.
    """
    mode = os.environ.get("CS230_FUSED_STEP", "auto").lower()
    return mode if mode in ("auto", "pallas", "legacy") else "auto"


def _packed_geometry(static, n, d, n_classes, n_splits):
    """Shared shape/penalty derivation of the packed path —
    ``build_batched_fn`` and ``batched_staged_extras`` must agree on
    every padded dimension or the staged forms would not match the
    executable's expectations."""
    c = max(int(n_classes), 2)
    fit_intercept = bool(static.get("fit_intercept", True))
    use_pen = static.get("penalty") in ("l2",)
    lam = (2.0 if n_classes == 2 else 1.0) if use_pen else 0.0
    dp = d + (1 if fit_intercept else 0)
    rc = 2048
    return {
        "c": c,
        "S": int(n_splits),
        "fit_intercept": fit_intercept,
        "lam": lam,
        "dp": dp,
        "dpp": _ceil_to(dp, 64),
        "rc": rc,
        "n_pad": _ceil_to(n, rc),
    }


def _packed_lam_max(A, TWp):
    """Per-split Lipschitz bound ``lam_max(A' diag(w) A)`` via a 30-step
    power iteration. Factored out so the inline path (legacy / direct
    calls) and the stage-cache precompute (``batched_staged_extras``) run
    the SAME formula — the precompute is keyed by (dataset fingerprint,
    fold-plan signature), which is exactly what this reads."""

    def lam_max_for(w):
        def power(v, _):
            u = A.T @ (w * (A @ v))
            return u / jnp.maximum(jnp.linalg.norm(u), 1e-12), None

        v0 = jnp.ones((A.shape[1],), jnp.float32)
        v, _ = jax.lax.scan(power, v0, None, length=30)
        return jnp.dot(v, A.T @ (w * (A @ v)))

    return jax.vmap(lam_max_for)(TWp)


def _make_masked_grad_fn(A, Y, y, w, C, lam, pen_mask, mm, mode):
    """Per-iteration masked-gradient closure for the grad-descent driver.

    The fused formulations eliminate the measured fold-mask overhead
    (benchmarks/LOGREG_PROFILE_MEASURED.json): the mask folds into the
    softmax normalizer (``w * softmax(z) == exp(z - max) * (w / den)``)
    and the masked label term ``w*Y`` is loop-invariant (hoisted out of
    the solver scan), so a masked iteration runs at most the op count of
    an unmasked one — no masked copy of A or of the probabilities is ever
    materialized.
    """
    if mode == "legacy":
        def grad_fn(W):
            P = jax.nn.softmax(mm(A, W), axis=-1)
            G = C * mm(A.T, w[:, None] * (P - Y)) + lam * pen_mask * W
            return G, P
        return grad_fn

    n, dp = A.shape
    c = Y.shape[1]
    use_pallas = mode == "pallas" or (
        mode == "auto"
        and not _interpret_mode()
        and jax.default_backend() == "tpu"
        and n >= 4096
    )
    if use_pallas:
        from ..ops.pallas_logreg import masked_softmax_grad

        bm = 256
        dpp = _ceil_to(dp, 128)
        cp = _ceil_to(c, 128)
        n_pad = _ceil_to(n, bm)
        # loop-invariant paddings: staged once per fit, reused every step
        Ab = jnp.pad(A.astype(jnp.float32), ((0, n_pad - n), (0, dpp - dp))).astype(
            jnp.bfloat16
        )
        y2 = jnp.pad(y.astype(jnp.int32), (0, n_pad - n))[:, None]
        wm = jnp.pad(w.astype(jnp.float32), (0, n_pad - n))[:, None]
        interp = jax.default_backend() != "tpu"

        def grad_fn(W):
            Wp = jnp.pad(W, ((0, dpp - dp), (0, cp - c))).astype(jnp.bfloat16)
            Gk = masked_softmax_grad(Ab, Wp, y2, wm, c=c, bm=bm, interpret=interp)
            G = C * Gk[:dp, :c] + lam * pen_mask * W
            return G, None
        return grad_fn

    WY = w[:, None] * Y  # loop-invariant: hoisted out of the solver scan

    def grad_fn(W):
        # w * softmax(Z) with the mask folded into the per-row normalizer:
        # e * (w/den) — an [n,1] divide replacing softmax's [n,c] divide,
        # so the masked iteration is never costlier than an unmasked one
        Z = mm(A, W)
        e = jnp.exp(Z - jnp.max(Z, axis=-1, keepdims=True))
        scale = (w / jnp.sum(e, axis=-1))[:, None]
        G = C * mm(A.T, e * scale - WY) + lam * pen_mask * W
        return G, None
    return grad_fn


def _trace_buf(steps, trace, shape=()):
    """(stride, buffer) for an in-scan grad-norm trace; ``(1, None)``
    when capture is off — ``None`` is an empty pytree, so the scan carry
    and jaxpr are bit-identical to the pre-curves path (the strict no-op
    contract tests/test_obs.py pins)."""
    if not trace:
        return 1, None
    from ..obs.curves import trace_stride

    stride = trace_stride(int(steps))
    used = -(-int(steps) // stride)
    return stride, jnp.zeros((used,) + tuple(shape), jnp.float32)


def _newton(A, Y, w, W0, mm, C, lam, pen_mask, max_iter, tol,
            steps=_NEWTON_STEPS, fused=True, trace=False):
    n, dp = A.shape
    c = Y.shape[1]
    dim = dp * c
    # tiny ridge on the unpenalized (intercept) entries breaks the softmax
    # gauge direction that would otherwise make the Hessian singular
    pen_diag = (lam * pen_mask + 1e-5 * (1.0 - pen_mask)).reshape(-1)

    def objective(W):
        logp = jax.nn.log_softmax(A @ W, axis=-1)
        nll = -jnp.sum(w * jnp.sum(Y * logp, axis=-1))
        return C * nll + 0.5 * jnp.sum((lam * pen_mask) * W * W)

    alphas = jnp.asarray([1.0, 0.5, 0.25, 0.1, 0.02], jnp.float32)
    # fused-mask restructuring: the masked label term wc*Y is loop-invariant
    # (hoisted out of the scan) and the single masked product WP = wc*P is
    # shared by the gradient AND both Hessian terms — the legacy per-step
    # masked copies of A (``A*wc``) and of the residual are never built
    WYc = (C * w)[:, None] * Y

    def grad_and_P(W):
        if not fused:
            P = jax.nn.softmax(mm(A, W), axis=-1)
            G = C * mm(A.T, w[:, None] * (P - Y)) + lam * pen_mask * W
            WP = (w * C)[:, None] * P
            return G, P, WP
        P = jax.nn.softmax(mm(A, W), axis=-1)
        WP = (w * C)[:, None] * P  # the one masked elementwise pass
        G = mm(A.T, WP - WYc) + lam * pen_mask * W
        return G, P, WP

    stride, tr0 = _trace_buf(steps, trace)

    def step(carry, t):
        W, done, tr = carry
        G, P, WP = grad_and_P(W)
        # Hessian: H[(i,a),(j,b)] = sum_n wc_n A_ni A_nj (P_na δab − P_na P_nb)
        # block-diagonal part: per class a, A' diag(wc * P_a) A == A' diag(WP_a) A
        blocks = jnp.einsum("ni,na,nj->aij", A, WP, A)  # [c, dp, dp]
        H = jnp.zeros((dp, c, dp, c), jnp.float32)
        H = H.at[:, jnp.arange(c), :, jnp.arange(c)].add(blocks)
        # rank-correction part: U' UW with U[n, dp*c] = A_ni * P_na and
        # UW = A_ni * WP_na (== (U * wc) without materializing a third
        # masked copy beyond WP itself)
        U = (A[:, :, None] * P[:, None, :]).reshape(n, dim)
        UW = (A[:, :, None] * WP[:, None, :]).reshape(n, dim)
        H = H.reshape(dim, dim) - U.T @ UW
        H = H + jnp.diag(pen_diag) + 1e-6 * jnp.eye(dim, dtype=jnp.float32)
        delta = jnp.linalg.solve(H, G.reshape(-1)).reshape(dp, c)
        # ill-conditioned solves (high C, saturated P, f32) can yield
        # non-finite deltas: fall back to a normalized gradient step
        delta_ok = jnp.all(jnp.isfinite(delta))
        gnorm = jnp.linalg.norm(G) + 1e-12
        delta = jnp.where(delta_ok, delta, G / gnorm)
        # backtracking: take the candidate step with the lowest objective
        # (guards against overshoot on separable data)
        objs = jax.vmap(lambda a: objective(W - a * delta))(alphas)
        best = jnp.argmin(objs)
        alpha = jnp.where(objs[best] < objective(W), alphas[best], 0.0)
        gmax = jnp.max(jnp.abs(G))
        active = jnp.logical_and(t < max_iter, jnp.logical_not(done))
        # select, don't multiply: 0 * non-finite delta would poison W
        take = jnp.logical_and(active, alpha > 0.0)
        W = jnp.where(take, W - alpha * delta, W)
        done = jnp.logical_or(done, gmax < tol)
        if trace:
            tr = tr.at[jnp.asarray(t, jnp.int32) // stride].set(gmax)
        return (W, done, tr), None

    (W, _, tr), _ = jax.lax.scan(
        step, (W0, jnp.asarray(False), tr0),
        jnp.arange(steps, dtype=jnp.float32)
    )
    return W, tr


def _nesterov(A, w, W0, grad_fn, C, lam, max_iter, tol, steps=_NESTEROV_STEPS,
              trace=False):
    # Lipschitz bound: L <= 0.5 * C * lambda_max(A' diag(w) A) + lam
    v = jnp.ones((A.shape[1],), jnp.float32)

    def power_step(v, _):
        u = A.T @ (w * (A @ v))
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-12), None

    v, _ = jax.lax.scan(power_step, v, None, length=30)
    lam_max = jnp.dot(v, A.T @ (w * (A @ v)))
    L = 0.5 * C * lam_max + lam + 1e-6
    step = 1.0 / L

    stride, tr0 = _trace_buf(steps, trace)

    def body(carry, t):
        W, W_prev, done, tr = carry
        mom = t / (t + 3.0)
        V = W + mom * (W - W_prev)
        G, _ = grad_fn(V)
        gmax = jnp.max(jnp.abs(G))
        active = jnp.logical_and(t < max_iter, jnp.logical_not(done))
        W_new = jnp.where(active, V - step * G, W)
        W_prev_new = jnp.where(active, W, W_prev)
        done = jnp.logical_or(done, gmax < tol)
        if trace:
            # gmax is evaluated unconditionally even once the lane is
            # done/past max_iter (the update is what's masked), so the
            # trace tail freezes at the converged gradient norm
            tr = tr.at[jnp.asarray(t, jnp.int32) // stride].set(gmax)
        return (W_new, W_prev_new, done, tr), None

    (W, _, _, tr), _ = jax.lax.scan(
        body,
        (W0, W0, jnp.asarray(False), tr0),
        jnp.arange(steps, dtype=jnp.float32),
    )
    return W, tr


# ---------------- out-of-core streamed Nesterov driver ----------------
#
# The single-shot path stages the full [n, dp] design matrix and lets
# jax.lax.scan drive _nesterov over it. Past the stage budget that staging
# is exactly the OOM the streaming layer exists to avoid, so this driver
# restructures the same solver around row blocks: every quantity the
# solver reduces over rows (the power-iteration application, the masked
# gradient, the weighted-accuracy numerator) becomes a sum of per-block
# partial reductions, accumulated in f32 across one streamed pass per
# solver step. Block order is fixed (ascending), so results are
# deterministic; they differ from the single-shot values only by f32
# summation order (tests/test_streaming.py pins the tolerance). The
# trial/split axes stay batched on device — resident state is
# W/W_prev/V/G at [T, S, dp, c] plus the fold tensors, independent of n.

_STREAM_FN_CACHE: Dict[Any, Any] = {}


def _stream_fns(rows, d, c, S, T, fit_intercept, lam):
    """Jitted per-block / per-iteration pieces of the streamed Nesterov
    solver, cached on geometry: the engine re-enters stream_scores once
    per trial chunk and every repeat chunk re-dispatches these."""
    key = (rows, d, c, S, T, bool(fit_intercept), float(lam))
    fns = _STREAM_FN_CACHE.get(key)
    if fns is not None:
        return fns

    from ..data.streaming import decode_block

    dp = d + (1 if fit_intercept else 0)
    pen = np.ones((dp, c), np.float32)
    if fit_intercept:
        pen[-1, :] = 0.0
    pen_mask = jnp.asarray(pen)

    def design(blk):
        return add_intercept(decode_block(blk), bool(fit_intercept))

    @jax.jit
    def power_block(blk, u, v, TW, start):
        # one block's contribution to u = A' diag(w) A v, all splits
        A = design(blk)
        wb = jax.lax.dynamic_slice(TW, (0, start), (S, rows))
        t = jnp.einsum("rd,sd->sr", A, v)
        return u + jnp.einsum("sr,rd->sd", wb * t, A)

    @jax.jit
    def power_norm(u):
        return u / jnp.maximum(
            jnp.linalg.norm(u, axis=1, keepdims=True), 1e-12
        )

    @jax.jit
    def extrapolate(W, Wp, t):
        mom = t / (t + 3.0)
        return W + mom * (W - Wp)

    @jax.jit
    def grad_block(blk, G, V, y_pad, TW, start):
        # the fused masked-gradient formulation of _make_masked_grad_fn,
        # restricted to one block: bf16 matmul inputs, f32 accumulation.
        # Pad rows have wb == 0, so both their softmax term and their
        # label term vanish exactly.
        A = design(blk)
        yb = jax.lax.dynamic_slice(y_pad, (start,), (rows,))
        wb = jax.lax.dynamic_slice(TW, (0, start), (S, rows))
        Z = jnp.einsum(
            "rd,tsdc->tsrc",
            A.astype(jnp.bfloat16), V.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        e = jnp.exp(Z - jnp.max(Z, axis=-1, keepdims=True))
        scale = wb[None] / jnp.sum(e, axis=-1)            # [T, S, rows]
        Yb = jax.nn.one_hot(yb, c, dtype=jnp.float32)
        WY = wb[:, :, None] * Yb[None]                    # [S, rows, c]
        R = e * scale[..., None] - WY[None]
        return G + jnp.einsum(
            "rd,tsrc->tsdc",
            A.astype(jnp.bfloat16), R.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )

    @jax.jit
    def update(W, Wp, V, G_raw, t, done, lam_max, C, max_iter, tol):
        # _nesterov's scan body, batched over (trial, split) lanes, with
        # the cross-block gradient sum supplied instead of grad_fn(V)
        G = C[:, None, None, None] * G_raw + lam * pen_mask[None, None] * V
        gmax = jnp.max(jnp.abs(G), axis=(2, 3))           # [T, S]
        L = 0.5 * C[:, None] * lam_max[None, :] + lam + 1e-6
        step = (1.0 / L)[:, :, None, None]
        active = jnp.logical_and(t < max_iter[:, None], jnp.logical_not(done))
        a4 = active[:, :, None, None]
        W_new = jnp.where(a4, V - step * G, W)
        Wp_new = jnp.where(a4, W, Wp)
        done = jnp.logical_or(done, gmax < tol[:, None])
        idle = jnp.logical_or(done, (t + 1.0) >= max_iter[:, None])
        return W_new, Wp_new, done, jnp.all(idle)

    @jax.jit
    def eval_block(blk, acc, W, y_pad, EW, start):
        # weighted-accuracy numerator, one block at a time (pad rows
        # carry zero eval weight); f32 logits like predict()
        A = design(blk)
        yb = jax.lax.dynamic_slice(y_pad, (start,), (rows,))
        ewb = jax.lax.dynamic_slice(EW, (0, start), (S, rows))
        Z = jnp.einsum("rd,tsdc->tsrc", A, W)
        hit = (jnp.argmax(Z, axis=-1) == yb[None, None, :]).astype(jnp.float32)
        return acc + jnp.einsum("sr,tsr->ts", ewb, hit)

    fns = (power_block, power_norm, extrapolate, grad_block, update, eval_block)
    _STREAM_FN_CACHE[key] = fns
    return fns


def _stream_nesterov_scores(streamer, y_pad, TW, EW, hyper_batch, static, n):
    n_classes = int(static["_n_classes"])
    c = max(n_classes, 2)
    fit_intercept = bool(static.get("fit_intercept", True))
    use_penalty = static.get("penalty") in ("l2",)
    lam = (1.0 if use_penalty else 0.0) * (2.0 if n_classes == 2 else 1.0)

    C = jnp.asarray(np.asarray(hyper_batch["C"], np.float32))
    max_iter = jnp.asarray(np.asarray(hyper_batch["max_iter"], np.float32))
    tol = jnp.asarray(np.asarray(hyper_batch["tol"], np.float32))
    T = int(C.shape[0])
    S = int(TW.shape[0])
    rows = int(streamer.plan.rows)
    d = int(streamer.row_shape[0])
    dp = d + (1 if fit_intercept else 0)
    steps = int(static.get("_iters", _NESTEROV_STEPS))

    power_block, power_norm, extrapolate, grad_block, update, eval_block = (
        _stream_fns(rows, d, c, S, T, fit_intercept, lam)
    )

    # Lipschitz bound: _nesterov's 30-step power iteration plus the
    # Rayleigh quotient — 31 streamed applications of A' diag(w) A
    v = jnp.ones((S, dp), jnp.float32)
    u = jnp.zeros((S, dp), jnp.float32)
    for it in range(31):
        u = jnp.zeros((S, dp), jnp.float32)
        for _i, start, blk in streamer.iter_blocks():
            u = power_block(blk, u, v, TW, jnp.asarray(start, jnp.int32))
        if it < 30:
            v = power_norm(u)
    lam_max = jnp.sum(v * u, axis=1)                      # [S]

    W = jnp.zeros((T, S, dp, c), jnp.float32)
    Wp = W
    done = jnp.zeros((T, S), bool)
    for t in range(steps):
        tf = jnp.asarray(t, jnp.float32)
        V = extrapolate(W, Wp, tf)
        G = jnp.zeros((T, S, dp, c), jnp.float32)
        for _i, start, blk in streamer.iter_blocks():
            G = grad_block(blk, G, V, y_pad, TW, jnp.asarray(start, jnp.int32))
        W, Wp, done, idle = update(
            W, Wp, V, G, tf, done, lam_max, C, max_iter, tol
        )
        # host-visible early exit: once every (trial, split) lane is
        # converged or past its max_iter, the remaining scan steps would
        # be masked no-ops — each costing a full pass over the blocks
        if bool(idle):
            break

    acc = jnp.zeros((T, S), jnp.float32)
    for _i, start, blk in streamer.iter_blocks():
        acc = eval_block(blk, acc, W, y_pad, EW, jnp.asarray(start, jnp.int32))
    den = jnp.maximum(jnp.sum(EW.astype(jnp.float32), axis=1), 1e-12)
    return np.asarray(acc / den[None, :], np.float32)
