"""LogisticRegression kernel: multinomial softmax regression, TPU-first.

Capability target: the reference's `LogisticRegression` trials
(``aws-prod/worker/worker.py:43``) — sklearn's L2-penalized logistic
regression (lbfgs solver), scored by accuracy and 5-fold CV. Instead of
per-trial CPU fits, this kernel is pure-functional and vmappable: one
compiled executable fits *all* trials in a bucket, with ``C``/``max_iter``/
``tol`` traced per-trial scalars.

Objective (matching sklearn): ``0.5 * ||W_coef||_F^2 + C * sum_i w_i *
xent_i`` with the intercept unpenalized. Two solvers, chosen at bucket-build
time from data shape (see ``resolve_static``):

- **newton**: exact full-Hessian Newton steps (quadratic convergence; the
  Hessian build is two MXU matmuls). Used when ``(d+1)*n_classes`` and the
  per-sample workspace are small. Converges to the same optimum as sklearn's
  lbfgs, so scores — and therefore ``best_params_`` — agree to tolerance.
- **nesterov**: accelerated full-batch gradient descent with a
  power-iteration Lipschitz step size, for large ``n*d*c`` (e.g. Covertype).
  Per-iteration cost is one [n,d]x[d,c] matmul — ideal MXU shape.

For binary problems sklearn fits a single logit; a 2-column softmax with the
penalty doubled has the same optimum predictive distribution (the penalty on
the logit difference matches), so we always use the softmax form and scale
the penalty by 2 when ``n_classes == 2``.

Known limitation: iteration counts are compile-time caps (``_NEWTON_STEPS``,
``_NESTEROV_STEPS``) because scan lengths are static; a per-trial
``max_iter`` below the cap is honored via masking, but one above it is
truncated. Newton's quadratic convergence makes 25 steps ample in practice;
the Nesterov path may under-converge vs sklearn lbfgs on hard problems —
revisit with an L-BFGS kernel if score-parity tests show drift.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .base import ModelKernel, add_intercept

_NEWTON_STEPS = 25
_NESTEROV_STEPS = 400
# newton only when the flattened Hessian dim and the [n, dp*c] workspace fit
_NEWTON_MAX_DIM = 512
_NEWTON_MAX_WORKSPACE = 4_000_000


class LogisticRegressionKernel(ModelKernel):
    name = "LogisticRegression"
    task = "classification"
    hyper_defaults = {"C": 1.0, "max_iter": 100.0, "tol": 1e-4}
    static_defaults = {"fit_intercept": True, "penalty": "l2"}

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if static.get("penalty") not in ("l2", None, "none"):
            raise ValueError(
                f"LogisticRegression penalty={static.get('penalty')!r} not supported"
            )
        c = max(int(n_classes), 2)
        dp = d + (1 if static.get("fit_intercept", True) else 0)
        method = (
            "newton"
            if dp * c <= _NEWTON_MAX_DIM and n * dp * c <= _NEWTON_MAX_WORKSPACE
            else "nesterov"
        )
        return {**static, "_method": method}

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        n_classes = int(static["_n_classes"])
        c = max(n_classes, 2)
        fit_intercept = bool(static.get("fit_intercept", True))
        use_penalty = static.get("penalty") in ("l2",)

        A = add_intercept(X, fit_intercept)
        dp = A.shape[1]
        Y = jax.nn.one_hot(y, c, dtype=jnp.float32)
        w = w.astype(jnp.float32)

        C = jnp.asarray(hyper["C"], jnp.float32)
        max_iter = jnp.asarray(hyper["max_iter"], jnp.float32)
        tol = jnp.asarray(hyper["tol"], jnp.float32)

        lam = jnp.where(use_penalty, 1.0, 0.0) * (2.0 if n_classes == 2 else 1.0)
        # intercept row is unpenalized (sklearn semantics)
        pen_mask = jnp.ones((dp, c), jnp.float32)
        if fit_intercept:
            pen_mask = pen_mask.at[-1, :].set(0.0)

        W0 = jnp.zeros((dp, c), jnp.float32)

        # large-n path: bf16 matmul inputs with f32 accumulation — the MXU's
        # native mode, ~4x the f32 throughput; Newton path stays f32 (its
        # Hessian solve is precision-sensitive and small anyway)
        if static["_method"] == "nesterov":
            def mm(a, b):
                return jnp.matmul(
                    a.astype(jnp.bfloat16),
                    b.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
        else:
            mm = jnp.matmul

        def grad_fn(W):
            P = jax.nn.softmax(mm(A, W), axis=-1)
            G = C * mm(A.T, w[:, None] * (P - Y)) + lam * pen_mask * W
            return G, P

        if static["_method"] == "newton":
            steps = int(static.get("_iters", _NEWTON_STEPS))
            W = _newton(A, Y, w, W0, grad_fn, C, lam, pen_mask, max_iter, tol, steps)
        else:
            steps = int(static.get("_iters", _NESTEROV_STEPS))
            W = _nesterov(A, w, W0, grad_fn, C, lam, max_iter, tol, steps)
        return W

    def bucket_static(self, static: Dict[str, Any], hypers) -> Dict[str, Any]:
        """Engine hook: with the bucket's hyper values known, cap the static
        scan length at the largest per-trial max_iter so masked-out
        iterations aren't executed at all."""
        cap = _NEWTON_STEPS if static["_method"] == "newton" else _NESTEROV_STEPS
        max_iters = [int(h.get("max_iter", 100)) for h in hypers] or [cap]
        return {**static, "_iters": max(1, min(cap, max(max_iters)))}

    def predict(self, params, X, static: Dict[str, Any]):
        fit_intercept = bool(static.get("fit_intercept", True))
        A = add_intercept(X, fit_intercept)
        return jnp.argmax(A @ params, axis=-1).astype(jnp.int32)

    def memory_estimate_mb(self, n, d, static):
        # marginal per-(trial,split) working set: a few [n, c] activation/
        # gradient buffers (the [n, d] design matrix is shared, not vmapped)
        c = max(int(static.get("_n_classes", 2)), 2)
        return max(1.0, 3.0 * 4.0 * n * c / 1e6)


def _newton(A, Y, w, W0, grad_fn, C, lam, pen_mask, max_iter, tol, steps=_NEWTON_STEPS):
    n, dp = A.shape
    c = Y.shape[1]
    dim = dp * c
    # tiny ridge on the unpenalized (intercept) entries breaks the softmax
    # gauge direction that would otherwise make the Hessian singular
    pen_diag = (lam * pen_mask + 1e-5 * (1.0 - pen_mask)).reshape(-1)

    def objective(W):
        logp = jax.nn.log_softmax(A @ W, axis=-1)
        nll = -jnp.sum(w * jnp.sum(Y * logp, axis=-1))
        return C * nll + 0.5 * jnp.sum((lam * pen_mask) * W * W)

    alphas = jnp.asarray([1.0, 0.5, 0.25, 0.1, 0.02], jnp.float32)

    def step(carry, t):
        W, done = carry
        G, P = grad_fn(W)
        wc = w * C
        # Hessian: H[(i,a),(j,b)] = sum_n wc_n A_ni A_nj (P_na δab − P_na P_nb)
        # block-diagonal part: per class a, A' diag(wc * P_a) A
        blocks = jnp.einsum("ni,na,nj->aij", A * wc[:, None], P, A)  # [c, dp, dp]
        H = jnp.zeros((dp, c, dp, c), jnp.float32)
        H = H.at[:, jnp.arange(c), :, jnp.arange(c)].add(blocks)
        # rank-correction part: U'WU with U[n, dp*c] = A_ni * P_na (one matmul)
        U = (A[:, :, None] * P[:, None, :]).reshape(n, dim)
        H = H.reshape(dim, dim) - U.T @ (U * wc[:, None])
        H = H + jnp.diag(pen_diag) + 1e-6 * jnp.eye(dim, dtype=jnp.float32)
        delta = jnp.linalg.solve(H, G.reshape(-1)).reshape(dp, c)
        # ill-conditioned solves (high C, saturated P, f32) can yield
        # non-finite deltas: fall back to a normalized gradient step
        delta_ok = jnp.all(jnp.isfinite(delta))
        gnorm = jnp.linalg.norm(G) + 1e-12
        delta = jnp.where(delta_ok, delta, G / gnorm)
        # backtracking: take the candidate step with the lowest objective
        # (guards against overshoot on separable data)
        objs = jax.vmap(lambda a: objective(W - a * delta))(alphas)
        best = jnp.argmin(objs)
        alpha = jnp.where(objs[best] < objective(W), alphas[best], 0.0)
        gmax = jnp.max(jnp.abs(G))
        active = jnp.logical_and(t < max_iter, jnp.logical_not(done))
        # select, don't multiply: 0 * non-finite delta would poison W
        take = jnp.logical_and(active, alpha > 0.0)
        W = jnp.where(take, W - alpha * delta, W)
        done = jnp.logical_or(done, gmax < tol)
        return (W, done), None

    (W, _), _ = jax.lax.scan(
        step, (W0, jnp.asarray(False)), jnp.arange(steps, dtype=jnp.float32)
    )
    return W


def _nesterov(A, w, W0, grad_fn, C, lam, max_iter, tol, steps=_NESTEROV_STEPS):
    # Lipschitz bound: L <= 0.5 * C * lambda_max(A' diag(w) A) + lam
    v = jnp.ones((A.shape[1],), jnp.float32)

    def power_step(v, _):
        u = A.T @ (w * (A @ v))
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-12), None

    v, _ = jax.lax.scan(power_step, v, None, length=30)
    lam_max = jnp.dot(v, A.T @ (w * (A @ v)))
    L = 0.5 * C * lam_max + lam + 1e-6
    step = 1.0 / L

    def body(carry, t):
        W, W_prev, done = carry
        mom = t / (t + 3.0)
        V = W + mom * (W - W_prev)
        G, _ = grad_fn(V)
        gmax = jnp.max(jnp.abs(G))
        active = jnp.logical_and(t < max_iter, jnp.logical_not(done))
        W_new = jnp.where(active, V - step * G, W)
        W_prev_new = jnp.where(active, W, W_prev)
        done = jnp.logical_or(done, gmax < tol)
        return (W_new, W_prev_new, done), None

    (W, _, _), _ = jax.lax.scan(
        body,
        (W0, W0, jnp.asarray(False)),
        jnp.arange(steps, dtype=jnp.float32),
    )
    return W
