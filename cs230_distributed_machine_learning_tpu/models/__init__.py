from .base import ModelKernel, TrialData
from .registry import get_kernel, register_kernel, supported_models

__all__ = ["ModelKernel", "TrialData", "get_kernel", "register_kernel", "supported_models"]
