"""GaussianNB kernel + single decision trees (beyond-whitelist estimators).

Not in the reference's 15-name whitelist but standard sklearn surface its
users expect; both are nearly free here: GaussianNB is three weighted
moment reductions, and DecisionTree* reuse the histogram tree core with
n_estimators=1 and no bootstrap/feature subsetting.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .base import ModelKernel
from .trees import _TreeBase

_EPS = 1e-9


class GaussianNBKernel(ModelKernel):
    name = "GaussianNB"
    task = "classification"
    hyper_defaults = {"var_smoothing": 1e-9}
    static_defaults: Dict[str, Any] = {}

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        X = X.astype(jnp.float32)
        w = w.astype(jnp.float32)
        Y = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]  # [n, c]
        counts = jnp.maximum(jnp.sum(Y, axis=0), _EPS)  # [c]
        mean = (Y.T @ X) / counts[:, None]  # [c, d]
        sq = (Y.T @ (X * X)) / counts[:, None]
        var = jnp.maximum(sq - mean**2, 0.0)
        # sklearn: var += var_smoothing * max feature variance
        wsum = jnp.maximum(jnp.sum(w), _EPS)
        gmean = jnp.sum(X * w[:, None], 0) / wsum
        gvar = jnp.sum(w[:, None] * (X - gmean) ** 2, 0) / wsum
        var = var + jnp.asarray(hyper["var_smoothing"], jnp.float32) * jnp.max(gvar)
        prior = counts / jnp.sum(counts)
        return {"mean": mean, "var": var, "log_prior": jnp.log(prior)}

    def _log_joint(self, params, X):
        X = X.astype(jnp.float32)
        mean, var = params["mean"], params["var"]  # [c, d]
        ll = -0.5 * jnp.sum(
            jnp.log(2 * jnp.pi * var)[None, :, :]
            + (X[:, None, :] - mean[None, :, :]) ** 2 / var[None, :, :],
            axis=-1,
        )
        return ll + params["log_prior"][None, :]

    def predict(self, params, X, static: Dict[str, Any]):
        return jnp.argmax(self._log_joint(params, X), axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static: Dict[str, Any]):
        lj = self._log_joint(params, X)
        return lj[:, 1] - lj[:, 0]

    def predict_proba(self, params, X, static: Dict[str, Any]):
        """Normalized joint likelihood (sklearn GaussianNB.predict_proba)."""
        return jax.nn.softmax(self._log_joint(params, X), axis=-1)


class _DecisionTreeBase(_TreeBase):
    _supports_deep = True  # sklearn default max_depth=None grows to purity
    static_defaults = {
        "max_depth": None,
        "min_samples_leaf": 1,
        "min_samples_split": 2,
        "max_features": None,
        "random_state": 0,
        "n_bins": 128,
        "criterion": "default",
        "splitter": "best",
        "min_weight_fraction_leaf": 0.0,
        "max_leaf_nodes": None,
        "min_impurity_decrease": 0.0,
        "ccp_alpha": 0.0,
        "monotonic_cst": None,
    }
    _mf_default = 1.0

    def _fit_tree(self, X, S, C, static):
        return self._fit_one_tree(
            X, S, C, static,
            jax.random.PRNGKey(static["_seed"]),
            jax.lax.Precision.HIGHEST,
        )


class DecisionTreeClassifierKernel(_DecisionTreeBase):
    name = "DecisionTreeClassifier"
    task = "classification"

    def fit(self, X, y, w, hyper, static):
        c = max(int(static["_n_classes"]), 2)
        w = w.astype(jnp.float32)
        S = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]
        params = {"tree": self._fit_tree(X, S, w, static)}
        if isinstance(X, dict):
            params["edges"] = X["edges"]
        return params

    def predict(self, params, X, static):
        xq = self._query_bins(params, X, static)
        proba = self._tree_predict(xq, params["tree"], static)
        return jnp.argmax(proba, axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static):
        xq = self._query_bins(params, X, static)
        proba = self._tree_predict(xq, params["tree"], static)
        return proba[:, 1] - proba[:, 0]

    def predict_proba(self, params, X, static):
        """Leaf class distribution (sklearn tree predict_proba); rows are
        S/C leaf frequencies, re-normalized defensively for empty leaves."""
        xq = self._query_bins(params, X, static)
        proba = self._tree_predict(xq, params["tree"], static)
        return proba / jnp.maximum(jnp.sum(proba, axis=-1, keepdims=True), 1e-12)


class DecisionTreeRegressorKernel(_DecisionTreeBase):
    name = "DecisionTreeRegressor"
    task = "regression"

    def fit(self, X, y, w, hyper, static):
        w = w.astype(jnp.float32)
        S = (y.astype(jnp.float32) * w)[:, None]
        params = {"tree": self._fit_tree(X, S, w, static)}
        if isinstance(X, dict):
            params["edges"] = X["edges"]
        return params

    def predict(self, params, X, static):
        xq = self._query_bins(params, X, static)
        return self._tree_predict(xq, params["tree"], static)[:, 0]


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(GaussianNBKernel())
register_kernel(DecisionTreeClassifierKernel())
register_kernel(DecisionTreeRegressorKernel())
