"""Model-kernel protocol: sklearn-estimator semantics as jittable JAX fits.

The reference executes each trial by instantiating a whitelisted sklearn
class from strings via exec/eval and calling ``.fit`` on CPU
(``aws-prod/worker/worker.py:36-57, 436-455``). Here every supported model
family is a *kernel*: a pure-functional ``fit``/``predict``/``evaluate``
triple that is jittable, vmappable over trials, and shardable over a TPU
mesh.

Hyperparameters are split into two groups per kernel:

- **traced hypers** — numeric values that can vary across trials inside one
  compiled executable (e.g. ``C``, ``alpha``). They arrive as a dict of
  scalars (one slice of a [T]-shaped batch) so a thousand-trial search
  compiles ONCE per static bucket, not a thousand times.
- **static config** — anything that changes shapes or control flow
  (``penalty`` kind, ``n_neighbors``, tree depth). Trials are bucketed by
  static config; each bucket is one compile.

This is the "hyperparameters-as-arrays" design called out in SURVEY.md §7
(compilation economics).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, Tuple

import numpy as np

from ..ops.metrics import (
    classification_score,
    margin_score,
    proba_score,
    regression_score,
    scoring_needs_margin,
    scoring_needs_proba,
    weighted_mse,
)


@dataclasses.dataclass(frozen=True)
class TrialData:
    """One dataset staged for trial execution. ``y`` is int32 class ids for
    classification (with ``n_classes`` > 0) or float32 targets for
    regression (``n_classes`` == 0)."""

    X: Any  # [n, d] float32
    y: Any  # [n]
    n_classes: int = 0

    @property
    def n_samples(self) -> int:
        return self.X.shape[0]

    @property
    def n_features(self) -> int:
        return self.X.shape[1]


class ModelKernel(abc.ABC):
    """Base class for all model kernels."""

    #: sklearn class name this kernel stands in for (e.g. "LogisticRegression")
    name: str = ""
    #: "classification" | "regression" | "transform"
    task: str = ""
    #: traced hyperparameter defaults, name -> float
    hyper_defaults: Dict[str, float] = {}
    #: static config defaults, name -> value
    static_defaults: Dict[str, Any] = {}
    #: sklearn get_params() noise with no bearing on the fitted function
    #: (execution knobs, deprecated placeholders) — dropped in canonicalize
    ignored_params: frozenset = frozenset(
        {
            "n_jobs",
            "verbose",
            "warm_start",
            "copy_X",
            "random_state",
            "solver",
            "multi_class",
            "dual",
            "intercept_scaling",
            "l1_ratio",
            "class_weight",
            "max_fun",
            "break_ties",
            "cache_size",
            "decision_function_shape",
            "store_cv_results",
            "copy",
            "algorithm",
            "leaf_size",
            "metric_params",
            "svd_solver",
            "iterated_power",
            "power_iteration_normalizer",
            "n_oversamples",
        }
    )

    def canonicalize(self, params: Dict[str, Any]) -> Tuple[Tuple, Dict[str, float]]:
        """Split a user parameter dict into (static_key, traced_hyper_dict).

        static_key is hashable and is the compile-bucket key. Unknown
        parameters land in the static key so they still form distinct
        buckets instead of being silently dropped.
        """
        hyper = dict(self.hyper_defaults)
        static = dict(self.static_defaults)
        for k, v in params.items():
            if k in self.hyper_defaults:
                hyper[k] = float(v)
            elif k in self.ignored_params or v == "deprecated" or (
                v is None and k not in self.static_defaults
            ):
                continue
            else:
                static[k] = v
        static_key = tuple(sorted((k, _hashable(v)) for k, v in static.items()))
        return static_key, hyper

    def static_from_key(self, static_key: Tuple) -> Dict[str, Any]:
        return {k: v for k, v in static_key}

    @abc.abstractmethod
    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        """Fit on rows selected by {0,1} weights ``w``; returns a params pytree.
        Must be pure and jittable."""

    @abc.abstractmethod
    def predict(self, params, X, static: Dict[str, Any]):
        """Predict labels/values for X. Pure, jittable."""

    def evaluate(self, params, X, y, w, static: Dict[str, Any]) -> Dict[str, Any]:
        """Score on rows selected by ``w``. Returns {"score": ...} plus
        task-specific extras. Default scoring matches the reference worker
        (accuracy for classifiers, r2 + MSE for regressors,
        worker.py:320-349); a job-level ``scoring`` (static ``_scoring``,
        from the search wrapper's cv_params) swaps in the matching jittable
        scorer from ops/metrics.py — honoring what the reference client
        captured but its worker dropped (core.py:135-138)."""
        scoring = static.get("_scoring")
        if self.task == "classification":
            if scoring_needs_margin(scoring):
                margin = self.predict_margin(params, X, static)
                return {"score": margin_score(scoring, y, margin, w)}
            if scoring_needs_proba(scoring):
                proba = self.predict_proba(params, X, static)
                return {"score": proba_score(
                    scoring, y, proba, w, static.get("_n_classes", 2)
                )}
            y_pred = self.predict(params, X, static)
            return {
                "score": classification_score(
                    scoring, y, y_pred, w, static.get("_n_classes", 2)
                )
            }
        y_pred = self.predict(params, X, static)
        return {
            "score": regression_score(scoring, y, y_pred, w),
            "mse": weighted_mse(y, y_pred, w),
        }

    def predict_margin(self, params, X, static: Dict[str, Any]):
        """Continuous decision score for the positive class (binary) —
        required by margin-based scorers (roc_auc). Kernels with a natural
        margin (logit difference, decision function) override this."""
        raise NotImplementedError(
            f"scoring requires a decision margin, which the {self.name} "
            "kernel does not expose (supported: kernels overriding "
            "predict_margin)"
        )

    def predict_proba(self, params, X, static: Dict[str, Any]):
        """Class-probability matrix [n, n_classes] — required by
        probability scorers (neg_log_loss, roc_auc_ovr/ovo). Kernels with
        natural probabilities (softmax logits, leaf class distributions,
        likelihoods) override this."""
        raise NotImplementedError(
            f"scoring requires class probabilities, which the {self.name} "
            "kernel does not expose (supported: kernels overriding "
            "predict_proba)"
        )

    # Rough per-trial working-set estimate in MB, used by the placement
    # engine's memory-aware scoring (parity with WorkerState.mem_load_mb,
    # scheduler_service.py:91-104). Kernels may override.
    def memory_estimate_mb(self, n: int, d: int, static: Dict[str, Any]) -> float:
        return max(1.0, 4.0 * n * max(d, 1) * 3 / 1e6)

    def trace_salt(self) -> Tuple:
        """Values read from the environment at TRACE time (solver step
        counts, landmark knobs, ...) that change the compiled program
        without appearing in ``static`` — they must key every executable
        cache, or a knob change silently loads the pre-knob blob. Kernels
        reading env at trace time must override."""
        return ()


def add_intercept(X, fit_intercept: bool):
    """[X | 1] design matrix when fitting an intercept (shared by the
    linear-family kernels)."""
    import jax.numpy as jnp

    X = X.astype(jnp.float32)
    if not fit_intercept:
        return X
    return jnp.concatenate([X, jnp.ones((X.shape[0], 1), jnp.float32)], axis=1)


def _hashable(v: Any):
    if isinstance(v, (list, np.ndarray)):
        return tuple(np.asarray(v).ravel().tolist())
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v
