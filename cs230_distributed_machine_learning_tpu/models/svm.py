"""SVM kernels (SVC / SVR), solved in the dual on-device.

Capability target: the reference's `SVC`/`SVR` trials
(``aws-prod/worker/worker.py:44,50``) — sklearn's RBF-kernel SVMs. The
reference fits libsvm's SMO on CPU per trial; SMO's sequential
working-set updates are hostile to XLA, so this kernel solves the same
box-constrained dual QP with *projected gradient ascent* and a
power-iteration Lipschitz step — every iteration is one [n,n]x[n] matvec
against the precomputed kernel Gram matrix, which XLA batches across
vmapped trials into MXU-sized matmuls.

The bias is handled by augmenting the kernel with a constant (+1) feature —
i.e. a (regularized-bias) SVM without the dual equality constraint. This is
the standard trick for first-order dual solvers; decision values differ from
libsvm only through the bias regularization and match to score tolerance on
real data (tests assert agreement with sklearn).

Multiclass SVC follows sklearn's one-vs-one scheme: c(c-1)/2 binary
machines fit with per-pair weight masks (more masked fits — free under
vmap), votes aggregated with sklearn's tie-breaking (first max).

Hypers: ``C`` (traced), ``epsilon`` for SVR (traced), ``gamma`` traced when
numeric; "scale"/"auto" resolve per-fit from the masked data like sklearn.
``kernel`` ("rbf" | "linear" | "poly") is static. Gram matrices are [n,n]
— fits are gated to moderate n (SVMs at Covertype scale are equally
intractable for the reference's libsvm workers).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .base import ModelKernel

_PG_STEPS = 600
_MAX_N = 30_000


def _gram(X1, X2, kernel: str, gamma, degree, coef0):
    if kernel == "linear":
        return X1 @ X2.T
    if kernel == "poly":
        return (gamma * (X1 @ X2.T) + coef0) ** degree
    # rbf
    d2 = (
        jnp.sum(X1 * X1, 1)[:, None]
        + jnp.sum(X2 * X2, 1)[None, :]
        - 2.0 * (X1 @ X2.T)
    )
    return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def _project_box_ascent(Q, lin, lo, hi, steps=_PG_STEPS):
    """max_a  lin.a - 0.5 a'Qa  s.t. lo <= a <= hi, by projected gradient
    with a power-iteration step size."""
    n = Q.shape[0]
    v = jnp.ones((n,), jnp.float32)

    def power(v, _):
        u = Q @ v
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-12), None

    v, _ = jax.lax.scan(power, v, None, length=25)
    L = jnp.maximum(jnp.dot(v, Q @ v), 1e-6)
    eta = 1.0 / L

    def body(a, _):
        g = lin - Q @ a
        a = jnp.clip(a + eta * g, lo, hi)
        return a, None

    a0 = jnp.zeros((n,), jnp.float32)
    a, _ = jax.lax.scan(body, a0, None, length=steps)
    return a


class SVCKernel(ModelKernel):
    name = "SVC"
    task = "classification"
    hyper_defaults = {"C": 1.0}
    static_defaults = {"kernel": "rbf", "gamma": "scale", "degree": 3, "coef0": 0.0}

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if n > _MAX_N:
            raise ValueError(f"SVC: n={n} exceeds the {_MAX_N}-sample Gram-matrix gate")
        if static.get("kernel") not in ("rbf", "linear", "poly"):
            raise ValueError(f"SVC: unsupported kernel {static.get('kernel')!r}")
        g = static.get("gamma", "scale")
        if isinstance(g, (int, float)):
            static = {**static, "_gamma_mode": "numeric", "_gamma_value": float(g)}
        else:
            static = {**static, "_gamma_mode": g}
        return static

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        X = X.astype(jnp.float32)
        w = w.astype(jnp.float32)
        c = max(int(static["_n_classes"]), 2)
        C = jnp.asarray(hyper["C"], jnp.float32)
        gamma = self._gamma(X, w, static)
        K = _gram(X, X, static["kernel"], gamma, static.get("degree", 3), static.get("coef0", 0.0))
        K = K + 1.0  # bias via constant feature in feature space

        pairs = [(i, j) for i in range(c) for j in range(i + 1, c)]

        def fit_pair(pa, pb):
            sel = ((y == pa) | (y == pb)) & (w > 0)
            s = sel.astype(jnp.float32)
            t = jnp.where(y == pa, 1.0, -1.0)  # +1 for class pa
            Q = (t[:, None] * t[None, :]) * K * (s[:, None] * s[None, :])
            # tiny diagonal keeps PG stable when rows are masked out
            Q = Q + 1e-6 * jnp.eye(K.shape[0], dtype=jnp.float32)
            alpha = _project_box_ascent(Q, s, 0.0, C * s)
            return alpha * t * s  # signed dual coefs for this pair

        pa = jnp.asarray([p[0] for p in pairs])
        pb = jnp.asarray([p[1] for p in pairs])
        coefs = jax.vmap(fit_pair)(pa, pb)  # [n_pairs, n]
        return {"X": X, "dual": coefs, "gamma": gamma, "pairs_a": pa, "pairs_b": pb}

    def predict(self, params, X, static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        Kq = _gram(
            X.astype(jnp.float32),
            params["X"],
            static["kernel"],
            params["gamma"],
            static.get("degree", 3),
            static.get("coef0", 0.0),
        ) + 1.0
        dec = Kq @ params["dual"].T  # [nq, n_pairs], >0 votes class pairs_a
        vote_a = (dec > 0).astype(jnp.float32)
        votes = jnp.zeros((X.shape[0], c), jnp.float32)
        votes = votes.at[:, params["pairs_a"]].add(vote_a)
        votes = votes.at[:, params["pairs_b"]].add(1.0 - vote_a)
        return jnp.argmax(votes, axis=-1).astype(jnp.int32)

    def _gamma(self, X, w, static):
        if static.get("_gamma_mode") == "numeric":
            return jnp.asarray(static["_gamma_value"], jnp.float32)
        if static.get("_gamma_mode") == "auto":
            return jnp.asarray(1.0 / X.shape[1], jnp.float32)
        w = w.astype(jnp.float32)
        wsum = jnp.maximum(jnp.sum(w), 1.0)
        mean = jnp.sum(X * w[:, None], 0) / wsum
        var = jnp.sum(w[:, None] * (X - mean) ** 2) / (wsum * X.shape[1])
        return 1.0 / jnp.maximum(X.shape[1] * var, 1e-12)

    def memory_estimate_mb(self, n, d, static):
        return max(1.0, 4.0 * (n * n * 2 + n * d) / 1e6)


class SVRKernel(ModelKernel):
    name = "SVR"
    task = "regression"
    hyper_defaults = {"C": 1.0, "epsilon": 0.1}
    static_defaults = {"kernel": "rbf", "gamma": "scale", "degree": 3, "coef0": 0.0}

    resolve_static = SVCKernel.resolve_static
    _gamma = SVCKernel._gamma
    memory_estimate_mb = SVCKernel.memory_estimate_mb

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        X = X.astype(jnp.float32)
        y = y.astype(jnp.float32)
        w = w.astype(jnp.float32)
        C = jnp.asarray(hyper["C"], jnp.float32)
        eps = jnp.asarray(hyper["epsilon"], jnp.float32)
        gamma = self._gamma(X, w, static)
        K = _gram(X, X, static["kernel"], gamma, static.get("degree", 3), static.get("coef0", 0.0)) + 1.0
        s = (w > 0).astype(jnp.float32)
        n = K.shape[0]
        # dual in beta = alpha - alpha*: max y.b - eps|b| - 0.5 b'Kb, |b|<=C.
        # |b| term handled by solving in the split form [alpha; alpha*]>=0.
        Ks = K * (s[:, None] * s[None, :]) + 1e-6 * jnp.eye(n, dtype=jnp.float32)
        Q = jnp.block([[Ks, -Ks], [-Ks, Ks]])
        lin = jnp.concatenate([(y - eps) * s, (-y - eps) * s])
        box = jnp.concatenate([C * s, C * s])
        a = _project_box_ascent(Q, lin, 0.0, box, steps=_PG_STEPS)
        beta = (a[:n] - a[n:]) * s
        return {"X": X, "dual": beta, "gamma": gamma}

    def predict(self, params, X, static: Dict[str, Any]):
        Kq = _gram(
            X.astype(jnp.float32),
            params["X"],
            static["kernel"],
            params["gamma"],
            static.get("degree", 3),
            static.get("coef0", 0.0),
        ) + 1.0
        return Kq @ params["dual"]


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(SVCKernel())
register_kernel(SVRKernel())
