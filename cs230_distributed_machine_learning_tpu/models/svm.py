"""SVM kernels (SVC / SVR), solved in the dual on-device.

Capability target: the reference's `SVC`/`SVR` trials
(``aws-prod/worker/worker.py:44,50``) — sklearn's RBF-kernel SVMs. The
reference fits libsvm's SMO on CPU per trial; SMO's sequential
working-set updates are hostile to XLA, so this kernel solves the same
box-constrained dual QP with *projected gradient ascent* and a
power-iteration Lipschitz step — every iteration is one [n,n]x[n] matvec
against the precomputed kernel Gram matrix, which XLA batches across
vmapped trials into MXU-sized matmuls.

The dual is solved with its REAL constraint set — the box AND the
``sum(t * alpha) = 0`` hyperplane (libsvm semantics): each ascent step
projects onto the intersection by bisection (`_project_box_hyperplane`,
O(n) per iteration), and the intercept comes from the KKT conditions over
free support vectors afterwards. (Round 3 replaced the earlier
regularized-bias K+1 approximation, which cost ~0.03-0.08 CV on
unbalanced Covertype class pairs; old artifacts predict through a
back-compat branch.)

Multiclass SVC follows sklearn's one-vs-one scheme: c(c-1)/2 binary
machines fit with per-pair weight masks (more masked fits — free under
vmap), votes aggregated with sklearn's tie-breaking (first max).

Hypers: ``C`` (traced), ``epsilon`` for SVR (traced), ``gamma`` traced when
numeric; "scale"/"auto" resolve per-fit from the masked data like sklearn.
``kernel`` ("rbf" | "linear" | "poly") is static.

Above ``_MAX_N`` samples the exact [n, n] Gram matrix is dropped for a
**Nyström primal** solve (round-1 verdict #5 — the 30k gate previously
errored at Covertype scale): m landmark rows give features
``Z = K(X, L) @ K_LL^{-1/2}`` (one [n,m,d] matmul + a [m,m] eigh), and each
machine solves the primal squared-hinge (SVC) / huberized
epsilon-insensitive (SVR) objective on Z with Nesterov descent — every
iteration one [n,m]x[m] matvec, batched across OvO pairs/vmapped trials on
the MXU. With the r4 solver budget (1200 steps — see ``_nystrom_steps``)
the full-Covertype SVC point measures CV 0.926, ABOVE exact sklearn SVC on
the 30k subsample it can actually complete (0.865); the reference's libsvm
workers could not complete the full fit at all (SMO is O(n^2..3) —
Covertype SVC would run for days).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .base import ModelKernel

_PG_STEPS = int(os.environ.get("CS230_SVM_PG_STEPS", "600"))
_MAX_N = 30_000


def _nystrom_steps() -> int:
    """Nesterov step count for the Nyström primal solve. The r3 default of
    300 was severely underconverged at full-Covertype scale (the analytic
    Lipschitz bound makes steps tiny): measured CV on the 116k-row SVC
    point was 0.834 @ 300 steps -> 0.897 @ 600 -> 0.926 @ 1200 -> 0.929
    @ 2400, at essentially FLAT wall time (~190 s; Z construction and
    prediction dominate, the [n,m] matvec iterations are cheap on the
    MXU). 1200 sits at the knee and takes the full-Covertype row past
    sklearn's 30k-subsample 0.865 (VERDICT r3 #6 asked for >=0.855)."""
    return int(os.environ.get("CS230_SVM_NYSTROM_STEPS", "1200"))


def _kmeans_iters() -> int:
    """Lloyd iterations refining the landmark set; DEFAULT 0 (off) — a
    measured negative result on Covertype-like data: k-means landmarks
    scored CV 0.798 where uniform rows scored 0.897 (same m=4096, same
    600-step solve). 44 of the 54 features are binary, so centroid
    averaging moves landmarks off the data manifold and degrades the
    Nyström basis; uniform rows are already on-manifold. The knob stays
    for continuous-feature datasets where coverage beats density."""
    return int(os.environ.get("CS230_SVM_KMEANS_ITERS", "0"))


def _nystrom_m(n: int) -> int:
    """Landmark count for the Nyström primal path, scaled with n: the
    rank-m approximation error is what separated full-Covertype SVC from
    sklearn's subsample score (VERDICT r2 #4b: -0.045 CV at flat m=2048).
    n/16 keeps the feature matrix Z [n, m] and the m^2 eigendecomposition
    affordable while roughly tracking the kernel spectrum the data adds;
    measured on v5e at 116k rows: m=4096 closes most of the flat-2048 gap
    (see tests/test_svm.py covertype tolerance)."""
    env = os.environ.get("CS230_SVM_NYSTROM_M")
    if env:
        return int(env)
    return int(min(4096, max(2048, n // 16)))


def _gram(X1, X2, kernel: str, gamma, degree, coef0):
    if kernel == "linear":
        return X1 @ X2.T
    if kernel == "poly":
        return (gamma * (X1 @ X2.T) + coef0) ** degree
    # rbf
    d2 = (
        jnp.sum(X1 * X1, 1)[:, None]
        + jnp.sum(X2 * X2, 1)[None, :]
        - 2.0 * (X1 @ X2.T)
    )
    return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def _kmeans_landmarks(X, init_centers, iters: int, chunk: int = 16384):
    """Lloyd's k-means refinement of the Nyström landmark set, fully
    on-device. OFF by default: on full Covertype this MEASURED WORSE
    than uniform rows (CV 0.798 vs 0.897 at the same m and solver
    budget) — 44/54 features are binary, and centroid averaging moves
    landmarks off the data manifold (see ``_kmeans_iters``). It remains
    available for continuous-feature data, where center coverage of the
    input space (not row density) bounds the Nyström approximation
    error. Each Lloyd iteration is two MXU matmuls per row chunk
    ([chunk,d]x[d,m] distances, then the one-hot-assignment
    accumulation [m,chunk]x[chunk,d]); rows stream through a lax.scan
    so the [n, m] distance matrix never materializes at full n."""
    n, d = X.shape
    C = init_centers
    m = C.shape[0]
    chunk = min(chunk, n)
    pad = (-n) % chunk
    Xp = jnp.pad(X, ((0, pad), (0, 0)))
    valid = jnp.pad(jnp.ones((n,), jnp.float32), (0, pad))
    Xc = Xp.reshape(-1, chunk, d)
    vc = valid.reshape(-1, chunk)

    def lloyd(C, _):
        cn = jnp.sum(C * C, axis=1)

        def chunk_step(carry, inp):
            sums, counts = carry
            xb, vb = inp
            d2 = cn[None, :] - 2.0 * (xb @ C.T)  # +||x||^2 is argmin-invariant
            a = jnp.argmin(d2, axis=1)
            onehot = jax.nn.one_hot(a, m, dtype=jnp.bfloat16) * vb[:, None].astype(jnp.bfloat16)
            sums = sums + jnp.matmul(
                onehot.T, xb.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            counts = counts + jnp.sum(onehot.astype(jnp.float32), axis=0)
            return (sums, counts), None

        (sums, counts), _ = jax.lax.scan(
            chunk_step,
            (jnp.zeros((m, d), jnp.float32), jnp.zeros((m,), jnp.float32)),
            (Xc, vc),
        )
        # empty clusters keep their previous center (stay a valid landmark)
        return jnp.where(counts[:, None] > 0.5,
                         sums / jnp.maximum(counts[:, None], 1.0), C), None

    C, _ = jax.lax.scan(lloyd, C, None, length=iters)
    return C


def _nystrom_features(X, landmarks, kernel: str, gamma, degree, coef0):
    """Z [n, m] with Z Z' ~ K(X, X): K(X, L) @ K(L, L)^{-1/2} (psd sqrt via
    eigh with a floor on the spectrum)."""
    KL = _gram(X, landmarks, kernel, gamma, degree, coef0)
    KLL = _gram(landmarks, landmarks, kernel, gamma, degree, coef0)
    vals, vecs = jnp.linalg.eigh(KLL)
    inv_sqrt = vecs * jax.lax.rsqrt(jnp.maximum(vals, 1e-6))[None, :]
    return KL @ inv_sqrt, inv_sqrt


def _nesterov_primal(Z, grad_fn, L_est, steps):
    """min_w f(w) by Nesterov descent with an analytic Lipschitz bound."""

    def body(carry, t):
        w, w_prev = carry
        v = w + (t / (t + 3.0)) * (w - w_prev)
        g = grad_fn(v)
        w_new = v - g / L_est
        return (w_new, w), None

    w0 = jnp.zeros((Z.shape[1],), jnp.float32)
    (w, _), _ = jax.lax.scan(
        body, (w0, w0), jnp.arange(steps, dtype=jnp.float32)
    )
    return w


def _matvec_f32(Q, v):
    """Q @ v with f32 accumulation whatever Q's storage dtype (the dual
    ascent stores Q/K in bf16 to halve the HBM stream that bounds it)."""
    return jnp.matmul(Q, v.astype(Q.dtype), preferred_element_type=jnp.float32)


def _lipschitz_eta(Q):
    """1/lambda_max(Q) step size by 25-iteration power method.

    The start vector is a fixed pseudo-random waveform: an all-ones start
    sits EXACTLY in the null space of the SVR block matrix [[K,-K],[-K,K]]
    (Q @ [u;u] = 0 by construction) and would leave the estimate riding on
    float rounding noise; any structured pattern risks a similar
    orthogonality accident (alternating signs re-enter that null space at
    even n). cos(1.7*i + 0.3) has non-negligible overlap with every
    eigenspace of interest and is deterministic across runs."""
    n = Q.shape[0]
    v = jnp.cos(1.7 * jnp.arange(n, dtype=jnp.float32) + 0.3)

    def power(v, _):
        u = _matvec_f32(Q, v)
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-12), None

    v, _ = jax.lax.scan(power, v, None, length=25)
    return 1.0 / jnp.maximum(jnp.dot(v, _matvec_f32(Q, v)), 1e-6)


def _project_box_hyperplane_cols(A_raw, TS, hi, iters: int = 30):
    """Euclidean projection of each column p of ``A_raw`` [n, P] onto
    {0 <= a <= hi[:, p], sum(TS[:, p] * a) = 0} (TS in {-1, 0, +1}):
    a(lam) = clip(A_raw - lam*TS, 0, hi); phi(lam) = sum(TS * a(lam)) is
    monotone non-increasing in lam per column, so per-column bisection
    finds the roots. O(nP) per iteration, fully vectorized."""
    def phi(lam):  # [P] -> [P]
        return jnp.sum(TS * jnp.clip(A_raw - lam[None, :] * TS, 0.0, hi), axis=0)

    span = jnp.max(hi) + jnp.max(jnp.abs(A_raw)) + 1.0
    lo_l = jnp.full((A_raw.shape[1],), -span)
    hi_l = jnp.full((A_raw.shape[1],), span)

    def body(carry, _):
        lo_l, hi_l = carry
        mid = 0.5 * (lo_l + hi_l)
        go_right = phi(mid) > 0
        return (jnp.where(go_right, mid, lo_l), jnp.where(go_right, hi_l, mid)), None

    (lo_l, hi_l), _ = jax.lax.scan(body, (lo_l, hi_l), None, length=iters)
    lam = 0.5 * (lo_l + hi_l)
    return jnp.clip(A_raw - lam[None, :] * TS, 0.0, hi)


def _project_box_hyperplane(a_raw, t, lo, hi, iters: int = 30):
    """Single-machine form: delegates to the column-batched projection
    (every call site uses lo = 0, which the cols form hardcodes)."""
    del lo  # always 0 at every call site; the cols form assumes it
    return _project_box_hyperplane_cols(
        a_raw[:, None], t[:, None],
        jnp.broadcast_to(hi, a_raw.shape)[:, None], iters,
    )[:, 0]


def _fista_ascent(qmatvec, project, lin, x0, eta, steps: int, tol: float,
                  scale, diag):
    """Shared FISTA loop (single and multi-machine duals): maximize
    lin.x - 0.5 x'Qx - 0.5 diag||x||^2 over the projection set, with the
    KKT displacement stop — a fixed point of project(x + eta*grad) IS a
    KKT point, so the loop exits when the iterate stops moving (relative
    to ``scale``, the box size)."""
    def cond(carry):
        x, x_prev, tk, k, res = carry
        live = res > tol * scale if tol > 0 else jnp.bool_(True)
        return (k < steps) & live

    def body(carry):
        x, x_prev, tk, k, _ = carry
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        y = x + ((tk - 1.0) / t_next) * (x - x_prev)
        g = lin - qmatvec(y) - diag * y
        x_new = project(y + eta * g)
        res = jnp.max(jnp.abs(x_new - x))
        return (x_new, x, t_next, k + 1, res)

    carry = (x0, x0, jnp.float32(1.0), jnp.int32(0), jnp.float32(jnp.inf))
    return jax.lax.while_loop(cond, body, carry)[0]


def _kkt_tol() -> float:
    """Early-stop tolerance for the dual ascent: iterate-displacement
    residual relative to the box scale (C). 0 disables the stop (fixed
    step count — the pre-r5 behavior, and the A/B baseline)."""
    return float(os.environ.get("CS230_SVM_KKT_TOL", "1e-3"))


def _constrained_dual_ascent(Q, lin, t, lo, hi, steps=None, diag=0.0):
    """max_a lin.a - 0.5 a'Qa s.t. lo <= a <= hi AND sum(t*a) = 0 — the
    C-SVM dual's REAL constraint set (libsvm semantics). The box-only form
    approximated the intercept by penalizing it into the kernel (K+1),
    which costs accuracy on unbalanced class pairs; projecting onto the
    box∩hyperplane intersection (bisection, _project_box_hyperplane) each
    step solves the constrained dual directly, and the intercept comes
    from the KKT conditions afterwards.

    r5: FISTA acceleration + KKT-residual early stop. Plain projected
    ascent with the 1/L step needs O(kappa) iterations; the Nesterov
    t-sequence extrapolation (accelerated projected gradient on the
    equivalent convex minimization) gets O(sqrt(kappa)) at identical
    per-step cost — the step is still one [n,n] matvec, the fit's
    HBM-bound term. The while_loop stops once the projected-iterate
    displacement falls below ``_kkt_tol() x box scale`` (a stationarity
    certificate for the projection operator: a fixed point of
    P_C(a + eta*grad) IS a KKT point), so easy (large-C-margin or
    small-subset OvO) machines stop in tens of iterations instead of
    burning the full budget. vmapped lanes run until the SLOWEST lane
    converges — still bounded by ``steps``."""
    if steps is None:
        steps = int(os.environ.get("CS230_SVM_PG_STEPS", _PG_STEPS))

    # the ascent is HBM-bound, not FLOP-bound: the [n, n] kernel operand
    # streams from memory on every step (~540 MB x 600 steps per OvO pair
    # at 11.6k rows in f32). RBF callers therefore pass Q ALREADY in bf16
    # — half the stream, 2.05x measured on the 11.6k-row Covertype model-
    # matrix fit (33.7 -> 16.4 s); its CV moved +0.001 (0.8161 -> 0.8171,
    # still at sklearn parity), inside what the box/hyperplane projection
    # absorbs for entries bounded in [0, 1]. ``diag`` applies the
    # stability ridge analytically in f32 — 1e-6 is below bf16 resolution
    # near 1.0, so it cannot ride inside a bf16 matrix.
    return _fista_ascent(
        qmatvec=lambda a: _matvec_f32(Q, a),
        project=lambda x: _project_box_hyperplane(x, t, lo, hi),
        lin=lin,
        x0=jnp.zeros((Q.shape[0],), jnp.float32),
        eta=_lipschitz_eta(Q),
        steps=steps,
        tol=_kkt_tol(),
        scale=jnp.maximum(jnp.max(hi - lo), 1e-12),
        diag=diag,
    )


def _constrained_dual_ascent_multi(Kb, lin, TS, hi, steps=None, diag=0.0):
    """ALL OvO machines of one fit in ONE ascent: A [n, P] dual columns.

    The per-pair form (vmap over ``_constrained_dual_ascent``) re-streams
    the SAME [n, n] kernel operand once per machine per iteration — at
    11.6k rows x 21 pairs x 6 fold lanes that is ~34 GB per iteration, and
    measured wall time was FLAT in the step cap because the stream, not
    the math, was the bill. Batched, each iteration is one
    [n, n] x [n, P] matmul: Kb streams ONCE per iteration per lane
    (~126x less HBM traffic), with Q's pair masks applied as elementwise
    TS factors (Q_p @ v = ts_p * (K @ (ts_p * v))). FISTA extrapolation
    and the KKT displacement stop carry over; the loop exits when the
    SLOWEST machine converges."""
    if steps is None:
        steps = int(os.environ.get("CS230_SVM_PG_STEPS", _PG_STEPS))
    tol = _kkt_tol()

    def qmatvec(V):  # [n, P] -> [n, P], f32 accumulation
        return TS * jnp.matmul(
            Kb, (TS * V).astype(Kb.dtype), preferred_element_type=jnp.float32
        )

    # per-machine 1/lambda_max by batched power iteration (the waveform
    # start rationale is in _lipschitz_eta)
    n, P = lin.shape
    v = jnp.broadcast_to(
        jnp.cos(1.7 * jnp.arange(n, dtype=jnp.float32) + 0.3)[:, None], (n, P)
    )

    def power(v, _):
        u = qmatvec(v)
        return u / jnp.maximum(jnp.linalg.norm(u, axis=0, keepdims=True), 1e-12), None

    v, _ = jax.lax.scan(power, v, None, length=25)
    lam_max = jnp.maximum(jnp.sum(v * qmatvec(v), axis=0), 1e-6)

    return _fista_ascent(
        qmatvec=qmatvec,
        project=lambda X: _project_box_hyperplane_cols(X, TS, hi),
        lin=lin,
        x0=jnp.zeros((n, P), jnp.float32),
        eta=(1.0 / lam_max)[None, :],
        steps=steps,
        tol=tol,
        scale=jnp.maximum(jnp.max(hi), 1e-12),
        diag=diag,
    )


class SVCKernel(ModelKernel):
    name = "SVC"
    task = "classification"
    hyper_defaults = {"C": 1.0}
    static_defaults = {"kernel": "rbf", "gamma": "scale", "degree": 3, "coef0": 0.0}

    def trace_salt(self):
        """Solver knobs read from env at trace time (module docstring) —
        they change the compiled program, so they must key the AOT cache
        (a knob flip must not load the pre-knob executable)."""
        return (
            int(os.environ.get("CS230_SVM_PG_STEPS", _PG_STEPS)),
            _nystrom_steps(),
            _kmeans_iters(),
            os.environ.get("CS230_SVM_NYSTROM_M", ""),
            os.environ.get("CS230_SVM_KKT_TOL", ""),
        )

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if static.get("kernel") not in ("rbf", "linear", "poly"):
            raise ValueError(f"SVC: unsupported kernel {static.get('kernel')!r}")
        g = static.get("gamma", "scale")
        if isinstance(g, (int, float)):
            static = {**static, "_gamma_mode": "numeric", "_gamma_value": float(g)}
        else:
            static = {**static, "_gamma_mode": g}
        if n > _MAX_N:
            # beyond the exact-Gram gate: Nyström primal (module docstring)
            static = {**static, "_nystrom": True, "_m": min(_nystrom_m(n), n)}
        return static

    # ---- shared Nyström-primal machinery (SVC + SVR) ----

    def _nystrom_Z(self, X, gamma, static):
        n = X.shape[0]
        m = int(static["_m"])
        idx = np.random.RandomState(17).choice(n, m, replace=False)
        landmarks = X[jnp.asarray(idx)]
        iters = _kmeans_iters()
        if iters > 0:
            landmarks = _kmeans_landmarks(X, landmarks, iters)
        Z, inv_sqrt = _nystrom_features(
            X, landmarks, static["kernel"], gamma,
            static.get("degree", 3), static.get("coef0", 0.0),
        )
        Z = jnp.concatenate([Z, jnp.ones((n, 1), jnp.float32)], axis=1)
        # Lipschitz ingredient: lambda_max(Z'Z) by power iteration
        v = jnp.ones((Z.shape[1],), jnp.float32)

        def power(v, _):
            u = Z.T @ (Z @ v)
            return u / jnp.maximum(jnp.linalg.norm(u), 1e-12), None

        v, _ = jax.lax.scan(power, v, None, length=20)
        lam_max = jnp.maximum(jnp.dot(v, Z.T @ (Z @ v)), 1e-6)
        return Z, landmarks, inv_sqrt, lam_max

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        X = X.astype(jnp.float32)
        w = w.astype(jnp.float32)
        c = max(int(static["_n_classes"]), 2)
        C = jnp.asarray(hyper["C"], jnp.float32)
        gamma = self._gamma(X, w, static)
        if static.get("_nystrom"):
            return self._fit_nystrom(X, y, w, C, gamma, static, c)
        K = _gram(X, X, static["kernel"], gamma, static.get("degree", 3), static.get("coef0", 0.0))
        # bf16 kernel operand for the ascent's matvec stream (the fit's
        # HBM-bound term, see _constrained_dual_ascent) — RBF only, whose
        # entries are bounded in [0, 1]; linear/poly Gram entries are
        # unbounded on unscaled data, where bf16's relative rounding could
        # swamp the O(1) linear term near convergence. The KKT intercept
        # below keeps the f32 K either way.
        Kb = K.astype(jnp.bfloat16) if static["kernel"] == "rbf" else K

        pairs = [(i, j) for i in range(c) for j in range(i + 1, c)]
        pa = jnp.asarray([p[0] for p in pairs])
        pb = jnp.asarray([p[1] for p in pairs])

        # ALL OvO machines in one batched ascent (A [n, P]): the per-pair
        # vmap re-streamed the [n, n] Gram once per machine per iteration
        # and was measured step-cap-FLAT at 13.7 s on the 11.6k model-
        # matrix row — the HBM stream, not the math, was the bill. See
        # _constrained_dual_ascent_multi. libsvm's actual dual: box AND
        # the sum(t*alpha)=0 hyperplane per machine; stability ridge
        # rides analytically (diag=1e-6).
        S = (((y[:, None] == pa[None, :]) | (y[:, None] == pb[None, :]))
             & (w > 0)[:, None]).astype(jnp.float32)  # [n, P]
        T = jnp.where(y[:, None] == pa[None, :], 1.0, -1.0)
        TS = T * S
        A = _constrained_dual_ascent_multi(Kb, S, TS, C * S, diag=1e-6)
        # KKT intercepts: average t_i - (margin) over FREE support vectors
        # (0 < alpha < C) per machine; fall back to all SVs
        F = jnp.matmul(K, A * TS, preferred_element_type=jnp.float32)
        free = S * (A > 1e-6 * C) * (A < C * (1.0 - 1e-6))
        anyv = S * (A > 1e-6 * C)
        use = jnp.where(jnp.sum(free, axis=0) > 0.5, free, anyv)
        b = jnp.sum(use * (T - F), axis=0) / jnp.maximum(
            jnp.sum(use, axis=0), 1e-6
        )
        return {"X": X, "dual": (A * TS).T, "intercept": b, "gamma": gamma,
                "pairs_a": pa, "pairs_b": pb}

    def _fit_nystrom(self, X, y, w, C, gamma, static, c):
        """Primal squared-hinge OvO machines on Nyström features."""
        Z, landmarks, inv_sqrt, lam_max = self._nystrom_Z(X, gamma, static)
        pairs = [(i, j) for i in range(c) for j in range(i + 1, c)]
        pa = jnp.asarray([p[0] for p in pairs])
        pb = jnp.asarray([p[1] for p in pairs])
        L_est = 1.0 + 2.0 * C * lam_max

        def fit_pair(cls_a, cls_b):
            s = (((y == cls_a) | (y == cls_b)) & (w > 0)).astype(jnp.float32)
            t = jnp.where(y == cls_a, 1.0, -1.0)

            def grad(wv):
                margin = jnp.maximum(0.0, 1.0 - t * (Z @ wv))
                return wv - 2.0 * C * (Z.T @ (s * t * margin))

            return _nesterov_primal(Z, grad, L_est, _nystrom_steps())

        W = jax.vmap(fit_pair)(pa, pb)  # [n_pairs, m+1]
        return {
            "W": W,
            "landmarks": landmarks,
            "inv_sqrt": inv_sqrt,
            "gamma": gamma,
            "pairs_a": pa,
            "pairs_b": pb,
        }

    def _pair_decisions(self, params, X, static: Dict[str, Any]):
        """[nq, n_pairs] OvO decision values; >0 votes pairs_a."""
        if "W" in params:
            Zq = _gram(
                X.astype(jnp.float32), params["landmarks"], static["kernel"],
                params["gamma"], static.get("degree", 3), static.get("coef0", 0.0),
            ) @ params["inv_sqrt"]
            Zq = jnp.concatenate([Zq, jnp.ones((X.shape[0], 1), jnp.float32)], 1)
            return Zq @ params["W"].T
        Kq = _gram(
            X.astype(jnp.float32),
            params["X"],
            static["kernel"],
            params["gamma"],
            static.get("degree", 3),
            static.get("coef0", 0.0),
        )
        dec = Kq @ params["dual"].T  # [nq, n_pairs], >0 votes class pairs_a
        if "intercept" in params:
            return dec + params["intercept"][None, :]
        # artifacts fitted before the KKT-intercept form: K+1 bias
        return dec + jnp.sum(params["dual"], axis=1)[None, :]

    def predict(self, params, X, static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        dec = self._pair_decisions(params, X, static)
        vote_a = (dec > 0).astype(jnp.float32)
        votes = jnp.zeros((X.shape[0], c), jnp.float32)
        votes = votes.at[:, params["pairs_a"]].add(vote_a)
        votes = votes.at[:, params["pairs_b"]].add(1.0 - vote_a)
        return jnp.argmax(votes, axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static: Dict[str, Any]):
        """Binary decision function, positive for class 1 (the single OvO
        pair's value is positive for pairs_a == class 0, hence the sign
        flip — matches sklearn's binary decision_function orientation)."""
        return -self._pair_decisions(params, X, static)[:, 0]

    def _gamma(self, X, w, static):
        if static.get("_gamma_mode") == "numeric":
            return jnp.asarray(static["_gamma_value"], jnp.float32)
        if static.get("_gamma_mode") == "auto":
            return jnp.asarray(1.0 / X.shape[1], jnp.float32)
        w = w.astype(jnp.float32)
        wsum = jnp.maximum(jnp.sum(w), 1.0)
        mean = jnp.sum(X * w[:, None], 0) / wsum
        var = jnp.sum(w[:, None] * (X - mean) ** 2) / (wsum * X.shape[1])
        return 1.0 / jnp.maximum(X.shape[1] * var, 1e-12)

    def memory_estimate_mb(self, n, d, static):
        if static.get("_nystrom"):
            m = int(static.get("_m", 2048)) + 1
            return max(1.0, 4.0 * (2.0 * n * m + n * d) / 1e6)
        return max(1.0, 4.0 * (n * n * 2 + n * d) / 1e6)


class SVRKernel(ModelKernel):
    name = "SVR"
    task = "regression"
    hyper_defaults = {"C": 1.0, "epsilon": 0.1}
    static_defaults = {"kernel": "rbf", "gamma": "scale", "degree": 3, "coef0": 0.0}

    resolve_static = SVCKernel.resolve_static
    trace_salt = SVCKernel.trace_salt
    _gamma = SVCKernel._gamma
    _nystrom_Z = SVCKernel._nystrom_Z
    memory_estimate_mb = SVCKernel.memory_estimate_mb

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        X = X.astype(jnp.float32)
        y = y.astype(jnp.float32)
        w = w.astype(jnp.float32)
        C = jnp.asarray(hyper["C"], jnp.float32)
        eps = jnp.asarray(hyper["epsilon"], jnp.float32)
        gamma = self._gamma(X, w, static)
        if static.get("_nystrom"):
            return self._fit_nystrom(X, y, w, C, eps, gamma, static)
        K = _gram(X, X, static["kernel"], gamma, static.get("degree", 3), static.get("coef0", 0.0))
        s = (w > 0).astype(jnp.float32)
        n = K.shape[0]
        # dual in beta = alpha - alpha*: max y.b - eps|b| - 0.5 b'Kb, |b|<=C,
        # AND sum(beta) = 0 (the intercept's constraint — same libsvm
        # semantics as the SVC fix above). Solved in the split form
        # [alpha; alpha*] >= 0 with t = [+1; -1] carrying the constraint.
        # masked Gram, computed once: the ascent streams it as bf16 (RBF
        # only — see the SVC fit note), the KKT intercept reuses the f32
        # form; the stability ridge moves to the analytic diag (1e-6 is
        # below bf16 resolution near 1.0 — it cannot ride inside a bf16
        # matrix, and its 1e-6*beta contribution to the intercept matvec
        # is noise)
        Ks = K * (s[:, None] * s[None, :])
        Ksb = Ks.astype(jnp.bfloat16) if static["kernel"] == "rbf" else Ks
        Q = jnp.block([[Ksb, -Ksb], [-Ksb, Ksb]])
        lin = jnp.concatenate([(y - eps) * s, (-y - eps) * s])
        box = jnp.concatenate([C * s, C * s])
        t = jnp.concatenate([s, -s])
        a = _constrained_dual_ascent(Q, lin, t, 0.0, box, diag=1e-6)
        beta = (a[:n] - a[n:]) * s
        # KKT intercept: free upper SVs sit on y - f = eps, free lower on
        # y - f = -eps
        f = Ks @ beta
        free_up = s * (a[:n] > 1e-6 * C) * (a[:n] < C * (1.0 - 1e-6))
        free_dn = s * (a[n:] > 1e-6 * C) * (a[n:] < C * (1.0 - 1e-6))
        num = jnp.sum(free_up * (y - f - eps)) + jnp.sum(free_dn * (y - f + eps))
        den = jnp.sum(free_up) + jnp.sum(free_dn)
        b = jnp.where(den > 0.5, num / jnp.maximum(den, 1e-6),
                      jnp.sum(s * (y - f)) / jnp.maximum(jnp.sum(s), 1e-6))
        return {"X": X, "dual": beta, "intercept": b, "gamma": gamma}

    def _fit_nystrom(self, X, y, w, C, eps, gamma, static):
        """Primal huberized epsilon-insensitive regression on Nyström
        features: l(r) = max(0, |r| - eps)^2."""
        Z, landmarks, inv_sqrt, lam_max = self._nystrom_Z(X, gamma, static)
        s = (w > 0).astype(jnp.float32)
        L_est = 1.0 + 2.0 * C * lam_max

        def grad(wv):
            r = Z @ wv - y
            dl = 2.0 * jnp.sign(r) * jnp.maximum(jnp.abs(r) - eps, 0.0)
            return wv + C * (Z.T @ (s * dl))

        wv = _nesterov_primal(Z, grad, L_est, _nystrom_steps())
        return {"W": wv, "landmarks": landmarks, "inv_sqrt": inv_sqrt, "gamma": gamma}

    def predict(self, params, X, static: Dict[str, Any]):
        if "W" in params:
            Zq = _gram(
                X.astype(jnp.float32), params["landmarks"], static["kernel"],
                params["gamma"], static.get("degree", 3), static.get("coef0", 0.0),
            ) @ params["inv_sqrt"]
            Zq = jnp.concatenate([Zq, jnp.ones((X.shape[0], 1), jnp.float32)], 1)
            return Zq @ params["W"]
        Kq = _gram(
            X.astype(jnp.float32),
            params["X"],
            static["kernel"],
            params["gamma"],
            static.get("degree", 3),
            static.get("coef0", 0.0),
        )
        out = Kq @ params["dual"]
        if "intercept" in params:
            return out + params["intercept"]
        # artifacts fitted before the KKT-intercept form used K+1 bias
        return out + jnp.sum(params["dual"])


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(SVCKernel())
register_kernel(SVRKernel())
