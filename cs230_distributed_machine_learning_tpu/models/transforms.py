"""Transformer kernels: StandardScaler, MinMaxScaler, PCA, OneHotEncoder,
SimpleImputer — jitted fit/transform.

Capability target: the five transformer entries of the reference's model
whitelist (``aws-prod/worker/worker.py:53-57``). Note the reference could
list but never actually *run* these — its training path assumes
classifier/regressor scoring (``worker.py:320-349``) — so here they get a
working contract instead: ``fit`` learns statistics on the weight-masked
rows, ``predict`` IS ``transform`` (returns the transformed matrix), and
``evaluate`` reports a transform-appropriate score (explained variance for
PCA, fraction of finite cells for the imputer, 1.0 for scalers) so search
jobs over transformer hyperparameters still rank.

TPU shape discipline: OneHotEncoder pads every column to a static
``max_categories`` width (one compile per cap) instead of data-dependent
output dims.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .base import ModelKernel

_EPS = 1e-12


class _TransformBase(ModelKernel):
    task = "transform"

    def evaluate(self, params, X, y, w, static: Dict[str, Any]) -> Dict[str, Any]:
        return {"score": jnp.asarray(1.0, jnp.float32)}


class StandardScalerKernel(_TransformBase):
    name = "StandardScaler"
    static_defaults = {"with_mean": True, "with_std": True}

    def fit(self, X, y, w, hyper, static):
        X = X.astype(jnp.float32)
        w = w.astype(jnp.float32)
        wsum = jnp.maximum(jnp.sum(w), _EPS)
        mean = jnp.sum(X * w[:, None], axis=0) / wsum
        var = jnp.sum(w[:, None] * (X - mean) ** 2, axis=0) / wsum
        return {"mean": mean, "scale": jnp.sqrt(jnp.maximum(var, _EPS))}

    def predict(self, params, X, static):
        X = X.astype(jnp.float32)
        if static.get("with_mean", True):
            X = X - params["mean"]
        if static.get("with_std", True):
            X = X / params["scale"]
        return X


class MinMaxScalerKernel(_TransformBase):
    name = "MinMaxScaler"
    static_defaults = {"feature_range": (0, 1), "clip": False}

    def fit(self, X, y, w, hyper, static):
        X = X.astype(jnp.float32)
        big = jnp.float32(3.4e38)
        sel = w[:, None] > 0
        return {
            "min": jnp.min(jnp.where(sel, X, big), axis=0),
            "max": jnp.max(jnp.where(sel, X, -big), axis=0),
        }

    def predict(self, params, X, static):
        lo, hi = static.get("feature_range", (0, 1))
        X = X.astype(jnp.float32)
        span = jnp.maximum(params["max"] - params["min"], _EPS)
        out = (X - params["min"]) / span * (hi - lo) + lo
        if static.get("clip", False):
            out = jnp.clip(out, lo, hi)
        return out


class PCAKernel(_TransformBase):
    name = "PCA"
    static_defaults = {"n_components": 2, "whiten": False}

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        nc = static.get("n_components") or min(n, d)
        if isinstance(nc, float) and 0 < nc < 1:
            raise ValueError("PCA: fractional n_components not supported (pass an int)")
        return {**static, "n_components": min(int(nc), d)}

    def fit(self, X, y, w, hyper, static):
        X = X.astype(jnp.float32)
        w = w.astype(jnp.float32)
        wsum = jnp.maximum(jnp.sum(w), _EPS)
        mean = jnp.sum(X * w[:, None], axis=0) / wsum
        Xc = (X - mean) * jnp.sqrt(w)[:, None]
        cov = (Xc.T @ Xc) / jnp.maximum(wsum - 1.0, 1.0)
        evals, evecs = jnp.linalg.eigh(cov)  # ascending
        k = int(static["n_components"])
        comps = evecs[:, ::-1][:, :k].T  # [k, d], descending eigenvalue order
        var = evals[::-1][:k]
        total = jnp.maximum(jnp.sum(evals), _EPS)
        return {
            "mean": mean,
            "components": comps,
            "explained_variance": var,
            "explained_variance_ratio": var / total,
        }

    def predict(self, params, X, static):
        Z = (X.astype(jnp.float32) - params["mean"]) @ params["components"].T
        if static.get("whiten", False):
            Z = Z / jnp.sqrt(jnp.maximum(params["explained_variance"], _EPS))
        return Z

    def evaluate(self, params, X, y, w, static):
        return {"score": jnp.sum(params["explained_variance_ratio"]).astype(jnp.float32)}


class OneHotEncoderKernel(_TransformBase):
    name = "OneHotEncoder"
    static_defaults = {"max_categories": 32}

    def fit(self, X, y, w, hyper, static):
        # columns are assumed integer-coded; remember per-column maximum so
        # transform can mask out-of-vocabulary codes
        X = X.astype(jnp.int32)
        sel = w[:, None] > 0
        return {"n_cats": jnp.max(jnp.where(sel, X, -1), axis=0) + 1}

    def predict(self, params, X, static):
        cap = int(static.get("max_categories", 32))
        X = X.astype(jnp.int32)
        oh = jax.nn.one_hot(X, cap, dtype=jnp.float32)  # [n, d, cap]
        valid = jnp.arange(cap)[None, :] < params["n_cats"][:, None]  # [d, cap]
        oh = oh * valid[None, :, :]
        n = X.shape[0]
        return oh.reshape(n, -1)


class SimpleImputerKernel(_TransformBase):
    name = "SimpleImputer"
    static_defaults = {"strategy": "mean", "fill_value": 0.0}

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if static.get("strategy") not in ("mean", "median", "constant"):
            raise ValueError(f"SimpleImputer: unsupported strategy {static.get('strategy')!r}")
        return dict(static)

    def fit(self, X, y, w, hyper, static):
        X = X.astype(jnp.float32)
        obs = jnp.isfinite(X) & (w[:, None] > 0)
        strategy = static.get("strategy", "mean")
        if strategy == "median":
            Xm = jnp.where(obs, X, jnp.nan)
            fill = jnp.nanmedian(Xm, axis=0)
        elif strategy == "constant":
            fill = jnp.full((X.shape[1],), float(static.get("fill_value", 0.0)), jnp.float32)
        else:
            cnt = jnp.maximum(jnp.sum(obs, axis=0), 1)
            fill = jnp.sum(jnp.where(obs, X, 0.0), axis=0) / cnt
        return {"fill": jnp.nan_to_num(fill)}

    def predict(self, params, X, static):
        X = X.astype(jnp.float32)
        return jnp.where(jnp.isfinite(X), X, params["fill"])

    def evaluate(self, params, X, y, w, static):
        out = self.predict(params, X, static)
        return {"score": jnp.mean(jnp.isfinite(out).astype(jnp.float32))}


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(StandardScalerKernel())
register_kernel(MinMaxScalerKernel())
register_kernel(PCAKernel())
register_kernel(OneHotEncoderKernel())
_imputer = SimpleImputerKernel()
register_kernel(_imputer)
# the reference whitelist spells it "Imputer" (worker.py:57)
_alias = SimpleImputerKernel()
_alias.name = "Imputer"
register_kernel(_alias)
