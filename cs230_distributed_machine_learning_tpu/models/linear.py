"""Linear regression family kernels (LinearRegression / Ridge).

Capability target: the reference's `LinearRegression` trials
(``aws-prod/worker/worker.py:48``), scored with r2 + MSE and 5-fold CV
(``worker.py:330-349``). Weighted least squares in closed form — a single
Cholesky-solved normal-equation system per (trial, split), which XLA batches
across the vmapped trial axis into one MXU-friendly batched solve.

Ridge (not in the reference whitelist but free here) shares the kernel with
a traced ``alpha``; LinearRegression is ``alpha=0`` with a tiny jitter for
conditioning.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from .base import ModelKernel, add_intercept


class LinearRegressionKernel(ModelKernel):
    name = "LinearRegression"
    task = "regression"
    hyper_defaults: Dict[str, float] = {}
    static_defaults = {"fit_intercept": True}

    #: traced ridge strength; 0 for plain least squares
    _alpha_default = 0.0

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        fit_intercept = bool(static.get("fit_intercept", True))
        y = y.astype(jnp.float32)
        w = w.astype(jnp.float32)
        A = add_intercept(X, fit_intercept)
        dp = A.shape[1]
        alpha = jnp.asarray(hyper.get("alpha", self._alpha_default), jnp.float32)
        pen = jnp.ones((dp,), jnp.float32)
        if fit_intercept:
            pen = pen.at[-1].set(0.0)
        Aw = A * w[:, None]
        # normal equations with unpenalized intercept + jitter for rank safety
        gram = A.T @ Aw + jnp.diag(alpha * pen + 1e-6)
        rhs = Aw.T @ y
        return jnp.linalg.solve(gram, rhs)

    def predict(self, params, X, static: Dict[str, Any]):
        fit_intercept = bool(static.get("fit_intercept", True))
        A = add_intercept(X, fit_intercept)
        return A @ params

    def macs_estimate(self, n, d, static):
        """Closed-form solve cost (host-vs-accelerator placement input)."""
        dp = d + 1
        return float(n * dp * dp + dp**3)


class RidgeKernel(LinearRegressionKernel):
    name = "Ridge"
    hyper_defaults = {"alpha": 1.0}
    _alpha_default = 1.0
