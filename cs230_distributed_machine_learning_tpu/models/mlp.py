"""MLP kernels (classifier + regressor), sklearn-MLP semantics on TPU.

Capability target: BASELINE.md config 5 (MLPClassifier RandomizedSearchCV on
MNIST — "stresses per-chip jit"). Mirrors sklearn's MLPClassifier/Regressor
defaults: relu hidden layers, minibatch Adam (batch 200), L2 penalty
``alpha``, log-loss / squared-loss. Architecture (``hidden_layer_sizes``),
activation, batch size, and epoch count are static (shape/trip-count);
``alpha`` and ``learning_rate_init`` are traced so learning-rate/penalty
sweeps share one compile.

Minibatching under the split-mask regime: batches are fixed random
permutation slices of the full (static-size) dataset with per-sample weights
multiplying the loss — rows outside the split contribute zero gradient, so
one compiled update serves all K+1 splits of every trial. The whole fit is
one ``lax.scan`` over epochs x batches of a jitted Adam step — exactly the
training-loop shape XLA pipelines best on TPU.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import ModelKernel

_EPOCH_CAP = 100


def _interpret_mode() -> bool:
    return os.environ.get("CS230_PALLAS_INTERPRET", "") == "1"


def _v_dtype_mode() -> str:
    """Storage dtype of the generic path's SECOND Adam moment:
    ``bf16`` (default — stochastic rounding, halves the dominant Adam-state
    HBM term) or ``f32`` (the pre-PR-6 layout, for A/B and rollback)."""
    mode = os.environ.get("CS230_MLP_V_DTYPE", "bf16").lower()
    return mode if mode in ("bf16", "f32") else "bf16"


def _sr_bf16(x32, key):
    """Stochastically round f32 -> bf16: add uniform bits below the bf16
    mantissa boundary, then truncate. Unbiased (E[q(x)] == x), so EMA
    updates smaller than bf16's round-to-nearest deadband accumulate in
    expectation instead of freezing — the property that makes a bf16
    second Adam moment safe (beta2=0.999 updates are ~0.1% of v, under
    the ~0.4% deadband). Inputs are non-negative finite EMAs; the add may
    carry into the exponent, which is exactly round-up."""
    u = jax.lax.bitcast_convert_type(x32.astype(jnp.float32), jnp.uint32)
    r = jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    u = (u + r) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(u, jnp.float32).astype(jnp.bfloat16)


def _act(name: str):
    return {
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "logistic": jax.nn.sigmoid,
        "identity": lambda x: x,
    }[name]


class _MLPBase(ModelKernel):
    hyper_defaults = {"alpha": 1e-4, "learning_rate_init": 1e-3}
    static_defaults = {
        "hidden_layer_sizes": (100,),
        "activation": "relu",
        "batch_size": "auto",
        "max_iter": 200,
        "random_state": 0,
        "solver": "adam",
        "beta_1": 0.9,
        "beta_2": 0.999,
        "epsilon": 1e-8,
        "shuffle": True,
        "early_stopping": False,
        "tol": 1e-4,
        "learning_rate": "constant",
        "momentum": 0.9,
        "n_iter_no_change": 10,
        "nesterovs_momentum": True,
        "power_t": 0.5,
        "validation_fraction": 0.1,
        "max_fun": 15000,
    }
    ignored_params = ModelKernel.ignored_params - {"random_state", "solver", "max_fun"}

    def trace_salt(self):
        """Fused-path env knobs read at trace time (lane packing) — they
        change the compiled program without landing in ``static``. The
        salt carries the EFFECTIVE boolean, not the raw string: only the
        exact value "1" changes pick_k, so "0"/"yes"/unset must share one
        cache key (a raw-string salt would force spurious retraces).
        CS230_CURVES joins: with capture on the Adam/SGD scans carry
        trace buffers and ``fit`` routes through value_and_grad, so the
        valve (and CS230_CURVE_POINTS) must re-key executables."""
        from ..obs.curves import curves_salt

        return (
            "1" if os.environ.get("CS230_MLP_K16") == "1" else "",
            _v_dtype_mode(),
            curves_salt(),
        )

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        hls = static.get("hidden_layer_sizes", (100,))
        if isinstance(hls, (int, float)):
            hls = (int(hls),)
        hls = tuple(int(h) for h in hls)
        bs = static.get("batch_size", "auto")
        bs = min(200, n) if bs == "auto" else min(int(bs), n)
        epochs = min(int(static.get("max_iter", 200)), _EPOCH_CAP)
        if static.get("activation", "relu") not in ("relu", "tanh", "logistic", "identity"):
            raise ValueError(f"MLP: unsupported activation {static.get('activation')!r}")
        if static.get("solver", "adam") not in ("adam", "sgd"):
            # lbfgs would silently train with the wrong optimizer — the
            # reference's sklearn honors it, so fail loudly instead
            raise ValueError(
                f"MLP: unsupported solver {static.get('solver')!r} "
                "(supported: adam, sgd)"
            )
        if static.get("learning_rate", "constant") not in (
            "constant", "invscaling", "adaptive"
        ):
            raise ValueError(
                f"MLP: unsupported learning_rate {static.get('learning_rate')!r}"
            )
        return {
            **static,
            "_hls": hls,
            "_bs": bs,
            "_epochs": epochs,
            "_seed": int(static.get("random_state") or 0),
        }

    def _dims(self, d: int, static: Dict[str, Any]) -> Tuple[int, ...]:
        out = self._out_dim(static)
        return (d, *static["_hls"], out)

    def macs_estimate(self, n, d, static):
        """fwd+bwd over all layer matmuls x epochs (3x fwd MAC rule)."""
        dims = self._dims(d, static)
        layer_macs = sum(a * b for a, b in zip(dims[:-1], dims[1:]))
        bs = int(static["_bs"])
        n_batches = max(1, n // bs)
        return 3.0 * static["_epochs"] * n_batches * bs * layer_macs

    def memory_estimate_mb(self, n, d, static):
        """Marginal per-(trial, split) working set: params + Adam moments +
        per-step batch activations — NOT the [n, d] dataset (shared across
        lanes, counted once by the engine). The base-class default charged
        each lane ~3x the dataset (~0.5 GB at MNIST scale), capping
        dispatches at ~2 trials and costing ~50 RPC round trips per job
        plus tiny-lane matmuls; the true footprint is a few MB, so the
        whole search fits one dispatch with hundreds of vmapped lanes."""
        dims = self._dims(d, static)
        wparams = sum(a * b + b for a, b in zip(dims[:-1], dims[1:]))
        bs = int(static.get("_bs", 200))
        # params (f32) + m + v (both bf16 by default; CS230_MLP_V_DTYPE=f32
        # widens v back to the pre-PR-6 layout)
        v_bytes = 2 if _v_dtype_mode() == "bf16" else 4
        state_mb = wparams * (4 + 2 + v_bytes) / 1e6
        act_mb = 3.0 * bs * sum(dims) * 4 / 1e6  # fwd+bwd live activations
        return max(1.0, state_mb + act_mb + 1.0)

    def _init(self, key, dims):
        """sklearn's Glorot-uniform init."""
        params = []
        for i in range(len(dims) - 1):
            key, sub = jax.random.split(key)
            fan_in, fan_out = dims[i], dims[i + 1]
            # sklearn uses factor 6 for relu/tanh/identity ("glorot")
            bound = jnp.sqrt(6.0 / (fan_in + fan_out))
            W = jax.random.uniform(sub, (fan_in, fan_out), jnp.float32, -bound, bound)
            params.append({"W": W, "b": jnp.zeros((fan_out,), jnp.float32)})
        return params

    def _forward(self, params, X, static, mm=None):
        act = _act(static.get("activation", "relu"))
        mm = mm or jnp.matmul
        h = X
        for layer in params[:-1]:
            h = act(mm(h, layer["W"]) + layer["b"])
        return mm(h, params[-1]["W"]) + params[-1]["b"]

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        return self._fit(X, y, w, hyper, static, trace=False)[0]

    def fit_curve(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        """Capture hook (docs/OBSERVABILITY.md "Trial telemetry plane"):
        same fit, plus bounded in-scan traces — per-step loss and
        grad-norm on the Adam path (``jax.value_and_grad`` replaces
        ``jax.grad``; the loss's forward pass is shared with the gradient
        so the extra cost is the two trace writes), per-epoch loss on the
        SGD path (already computed for the adaptive schedule). Returns
        ``(params, curve)``."""
        return self._fit(X, y, w, hyper, static, trace=True)

    def _fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any],
             trace: bool):
        X = X.astype(jnp.float32)
        w = w.astype(jnp.float32)
        n, d = X.shape
        bs = static["_bs"]
        epochs = static["_epochs"]
        n_batches = max(1, n // bs)
        alpha = jnp.asarray(hyper["alpha"], jnp.float32)
        lr = jnp.asarray(hyper["learning_rate_init"], jnp.float32)
        b1 = float(static.get("beta_1", 0.9))
        b2 = float(static.get("beta_2", 0.999))
        eps = float(static.get("epsilon", 1e-8))

        dims = self._dims(d, static)
        key = jax.random.PRNGKey(static["_seed"])
        key, init_key = jax.random.split(key)
        params = self._init(init_key, dims)
        target = self._target(y, static)

        # bf16 matmuls (f32 accumulation) for the fwd/bwd passes — the MXU's
        # native mode; and bf16 moments. The fit is Adam-STATE-bandwidth
        # bound, not compute bound (params+m+v stream from HBM every step
        # while each step's matmul touches only batch_size rows), so
        # shrinking moment bytes matters more than the matmul rate.
        # The second moment needs care: beta2=0.999 makes per-step updates
        # ~0.1% of v, below bf16's ~0.4% round-to-nearest deadband — a
        # nearest-rounded bf16 v freezes at stale values and silently
        # suppresses the effective step size (m's beta1=0.9 steps are ~25x
        # the deadband, safe with nearest rounding). A bf16 v is therefore
        # stored with STOCHASTIC rounding: the quantizer is unbiased, so
        # sub-deadband updates land in expectation instead of vanishing
        # (convergence-parity vs the f32 v pinned in tests/test_mlp.py;
        # CS230_MLP_V_DTYPE=f32 restores the old state layout).
        def mm(a, b):
            return jnp.matmul(
                a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )

        def loss_fn(p, xb, tb, wb):
            # sklearn scaling: mean batch loss + alpha/2 * ||W||^2 / batch size,
            # with split-mask weights zeroing out-of-split rows
            pred = self._forward(p, xb, static, mm=mm)
            batch_w = jnp.maximum(jnp.sum(wb), 1e-12)
            data_loss = jnp.sum(self._loss(pred, tb) * wb) / batch_w
            l2 = sum(jnp.sum(layer["W"] ** 2) for layer in p)
            return data_loss + 0.5 * alpha * l2 / batch_w

        grad_fn = jax.value_and_grad(loss_fn) if trace else jax.grad(loss_fn)

        total_steps = epochs * n_batches
        if trace:
            from ..obs.curves import trace_stride

            tr_stride = trace_stride(total_steps)
            tr_used = -(-total_steps // tr_stride)
            tr0 = (jnp.zeros((tr_used,), jnp.float32),
                   jnp.zeros((tr_used,), jnp.float32))
        else:
            tr_stride, tr0 = 1, None

        bf16 = jnp.bfloat16
        v_bf16 = _v_dtype_mode() == "bf16"
        m0 = jax.tree_util.tree_map(lambda a: jnp.zeros_like(a, bf16), params)
        v0 = jax.tree_util.tree_map(
            lambda a: jnp.zeros_like(a, bf16 if v_bf16 else jnp.float32), params
        )
        sr_key = jax.random.fold_in(key, 0x5A)  # stochastic-rounding stream

        def step(carry, inp):
            p, m, v, t, tr = carry
            idx = inp
            xb = X[idx]
            tb = target[idx]
            wb = w[idx]
            if trace:
                loss, g = grad_fn(p, xb, tb, wb)
                gmax = jnp.max(jnp.asarray(
                    [jnp.max(jnp.abs(leaf))
                     for leaf in jax.tree_util.tree_leaves(g)]
                ))
                ti = jnp.asarray(t, jnp.int32) // tr_stride
                tr = (tr[0].at[ti].set(loss), tr[1].at[ti].set(gmax))
            else:
                g = grad_fn(p, xb, tb, wb)
            t = t + 1.0
            # moment math in f32, storage in bf16 (carry dtype)
            m = jax.tree_util.tree_map(
                lambda a, b: (b1 * a.astype(jnp.float32) + (1 - b1) * b
                              ).astype(bf16), m, g)
            if v_bf16:
                # unbiased bf16 storage: per-step, per-leaf random bits
                # derived from the (fit-seed, step) pair keep the scan
                # carry free of PRNG state
                kt = jax.random.fold_in(sr_key, t.astype(jnp.int32))
                leaves, treedef = jax.tree_util.tree_flatten(v)
                vkeys = jax.tree_util.tree_unflatten(
                    treedef, list(jax.random.split(kt, len(leaves)))
                )
                v = jax.tree_util.tree_map(
                    lambda a, b, k: _sr_bf16(
                        b2 * a.astype(jnp.float32) + (1 - b2) * b * b, k
                    ),
                    v, g, vkeys,
                )
            else:
                v = jax.tree_util.tree_map(
                    lambda a, b: b2 * a + (1 - b2) * b * b, v, g)
            mhat = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32) / (1 - b1**t), m)
            vhat = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32) / (1 - b2**t), v)
            p = jax.tree_util.tree_map(
                lambda a, mh, vh: a - lr * mh / (jnp.sqrt(vh) + eps), p, mhat, vhat
            )
            return (p, m, v, t, tr), None

        # precompute shuffled batch indices for all epochs: [epochs*n_batches, bs]
        def epoch_perm(k):
            return jax.random.permutation(k, n)[: n_batches * bs].reshape(n_batches, bs)

        perm_keys = jax.random.split(key, epochs)
        batches = jax.vmap(epoch_perm)(perm_keys).reshape(-1, bs)

        if static.get("solver", "adam") == "sgd":
            return self._fit_sgd(
                X, target, w, params, batches.reshape(epochs, n_batches, bs),
                loss_fn, lr, static, n, trace=trace,
            )

        (params, _, _, _, tr), _ = jax.lax.scan(
            step, (params, m0, v0, jnp.asarray(0.0), tr0), batches
        )
        if not trace:
            return params, None
        return params, {
            "loss": tr[0],
            "gmax": tr[1],
            "stride": jnp.asarray(float(tr_stride), jnp.float32),
            "steps": jnp.asarray(float(total_steps), jnp.float32),
        }

    def _fit_sgd(self, X, target, w, params, batches, loss_fn, lr0, static, n,
                 trace=False):
        """sklearn SGDOptimizer semantics: velocity momentum (plain or
        Nesterov) with the three learning-rate schedules —
        ``constant``; ``invscaling`` lr = lr_init / (t+1)^power_t with t
        advancing by n samples per epoch (sklearn's ``t_``); ``adaptive``
        divides lr by 5 once the epoch loss fails to improve by ``tol``
        for n_iter_no_change+1 consecutive epochs (floored at 1e-6).
        Like the Adam path, tol-based EARLY STOPPING is not applied — the
        full max_iter budget runs (a documented simplification; the lr
        schedule itself is honored)."""
        momentum = float(static.get("momentum", 0.9))
        nesterov = bool(static.get("nesterovs_momentum", True))
        schedule = static.get("learning_rate", "constant")
        power_t = float(static.get("power_t", 0.5))
        tol = float(static.get("tol", 1e-4))
        no_change = int(static.get("n_iter_no_change", 10))
        tmap = jax.tree_util.tree_map

        def batch_step(carry, idx):
            p, vel, lr_t = carry
            loss, g = jax.value_and_grad(loss_fn)(p, X[idx], target[idx], w[idx])
            vel = tmap(lambda v, gg: momentum * v - lr_t * gg, vel, g)
            if nesterov:
                p = tmap(lambda a, v, gg: a + momentum * v - lr_t * gg, p, vel, g)
            else:
                p = tmap(lambda a, v: a + v, p, vel)
            return (p, vel, lr_t), loss

        epochs = int(batches.shape[0])
        if trace:
            from ..obs.curves import trace_stride

            tr_stride = trace_stride(epochs)
            tr_used = -(-epochs // tr_stride)
            tr0 = jnp.zeros((tr_used,), jnp.float32)
        else:
            tr_stride, tr0 = 1, None

        def epoch_step(carry, xs):
            p, vel, lr_t, t_samples, best, wait, tr = carry
            ebatches, e_idx = xs
            (p, vel, _), losses = jax.lax.scan(
                batch_step, (p, vel, lr_t), ebatches
            )
            epoch_loss = jnp.mean(losses)
            if trace:
                tr = tr.at[e_idx // tr_stride].set(epoch_loss)
            t_samples = t_samples + n
            if schedule == "invscaling":
                lr_t = lr0 / (t_samples + 1.0) ** power_t
            elif schedule == "adaptive":
                improved = epoch_loss < best - tol
                wait = jnp.where(improved, 0, wait + 1)
                cut = wait > no_change
                lr_t = jnp.where(cut, jnp.maximum(lr_t / 5.0, 1e-6), lr_t)
                wait = jnp.where(cut, 0, wait)
                best = jnp.minimum(best, epoch_loss)
            return (p, vel, lr_t, t_samples, best, wait, tr), None

        vel0 = tmap(jnp.zeros_like, params)
        (params, _, _, _, _, _, tr), _ = jax.lax.scan(
            epoch_step,
            (params, vel0, lr0 * jnp.asarray(1.0, jnp.float32),
             jnp.asarray(0.0, jnp.float32),
             jnp.asarray(jnp.inf, jnp.float32), jnp.asarray(0, jnp.int32),
             tr0),
            (batches, jnp.arange(epochs, dtype=jnp.int32)),
        )
        if not trace:
            return params, None
        return params, {
            "loss": tr,
            "stride": jnp.asarray(float(tr_stride), jnp.float32),
            "steps": jnp.asarray(float(epochs), jnp.float32),
        }


    # ---- fused Pallas batched path (ops/pallas_mlp.py) -------------------
    #
    # On TPU, adam/constant-lr buckets at real-data scale bypass the generic
    # vmap engine: the whole epoch's minibatch loop runs as ONE Pallas
    # kernel with (params, m, v) resident in VMEM and k (trial x split)
    # lanes packed per grid step. The generic path streams ~20 B of Adam
    # state per param per STEP through HBM — the measured 7.3%-MFU floor at
    # MNIST scale (VERDICT r3 #4); the fused kernel pays that per EPOCH.

    batched_trial_multiple = 1
    batched_chunk_cap = 64

    def batched_applicable(self, static: Dict[str, Any], n: int, d: int) -> bool:
        solver = static.get("solver", "adam")
        if solver not in ("adam", "sgd"):
            return False
        # learning_rate schedules are sgd-only in sklearn (adam ignores
        # them); all three ride the fused path — constant/invscaling as a
        # per-epoch lr column, adaptive via the kernel's epoch-loss slab
        if not static.get("shuffle", True) or static.get("early_stopping"):
            return False
        if len(static["_hls"]) > 3:
            return False
        # non-8-multiple batch sizes pad each batch block with zero-weight
        # slots (sublane rule); no eligibility cut needed
        if _interpret_mode():
            return True
        return jax.default_backend() == "tpu" and n >= 4096

    def build_batched_fn(self, static, n, d, n_classes, n_splits, chunk):
        """fn(X, y, TW, EW, hyper) -> {"score": [chunk, n_splits]} (+"mse"
        for regressors) — fit via the fused Pallas epoch kernel, eval in
        XLA. Same contract as the engine's vmapped executable."""
        if not self.batched_applicable(static, n, d):
            return None

        from ..ops.pallas_mlp import build_epoch_fn, pick_k

        interpret = _interpret_mode()
        classification = self.task == "classification"
        c = self._out_dim(static)
        dims = self._dims(d, static)
        act = static.get("activation", "relu")
        bs = int(static["_bs"])
        epochs = int(static["_epochs"])
        n_batches = max(1, n // bs)
        R = n_batches * bs
        # TPU sublane rule: batch blocks pad to a multiple of 8 rows; pad
        # slots replay row 0 with zero weight (no gradient contribution)
        bs_pad = -(-bs // 8) * 8
        S = int(n_splits)
        L0 = chunk * S
        solver = static.get("solver", "adam")
        schedule = static.get("learning_rate", "constant")
        adaptive = solver == "sgd" and schedule == "adaptive"
        k = pick_k(dims, bs_pad, solver=solver)
        Lk = -(-L0 // k) * k
        seed = int(static["_seed"])
        b1 = float(static.get("beta_1", 0.9))
        b2 = float(static.get("beta_2", 0.999))
        eps = float(static.get("epsilon", 1e-8))
        momentum = float(static.get("momentum", 0.9))
        nesterov = bool(static.get("nesterovs_momentum", True))
        power_t = float(static.get("power_t", 0.5))
        tol = float(static.get("tol", 1e-4))
        no_change = int(static.get("n_iter_no_change", 10))
        # the kernel hardcodes sklearn's Adam constants; non-default values
        # must take the generic path, which honors them
        if solver == "adam" and (b1, b2, eps) != (0.9, 0.999, 1e-8):
            return None

        # lane = trial * S + split; padded lanes replay lane 0 (discarded)
        ls_np = np.concatenate(
            [np.arange(L0, dtype=np.int32) % S,
             np.zeros(Lk - L0, dtype=np.int32)]
        )
        lane_split = jnp.asarray(ls_np)
        epoch_fn = build_epoch_fn(
            dims, act, bs_pad, n_batches, Lk, k, classification,
            solver=solver, momentum=momentum, nesterov=nesterov,
            track_loss=adaptive, interpret=interpret,
        )

        def _lane_vec(h):  # [chunk] hyper -> [Lk, 1] per-lane column
            v = jnp.repeat(h.astype(jnp.float32), S)
            v = jnp.concatenate([v, jnp.broadcast_to(v[:1], (Lk - L0,))])
            return v[:, None]

        rc = 256  # eval row chunk: [Lk, rc, max_h] activations stay <200 MB
        n_pad = -(-n // rc) * rc
        # matmul operand dtype: bf16 on the MXU; the CPU interpreter (test
        # coverage) lacks the mixed bf16->f32 dot
        mdt = jnp.float32 if interpret else jnp.bfloat16

        def fn(X, y, TW, EW, hyper):
            Xb = X.astype(mdt)
            if classification:
                Y = jax.nn.one_hot(y, c, dtype=jnp.bfloat16)
            else:
                Y = y.astype(jnp.float32)[:, None]
            TWf = TW.astype(jnp.float32)
            lr = _lane_vec(hyper["learning_rate_init"])
            alpha = _lane_vec(hyper["alpha"])

            key = jax.random.PRNGKey(seed)
            key, init_key = jax.random.split(key)
            params = self._init(init_key, dims)
            per_layer = 6 if solver == "adam" else 4
            n_moments = 2 if solver == "adam" else 1
            state = []
            for layer in params:
                # biases ride as [Lk, 8, out] row-identical slabs (see
                # ops/pallas_mlp.py kernel docstring for the layout rule)
                for leaf in (layer["W"], jnp.tile(layer["b"][None, :], (8, 1))):
                    state.append(jnp.tile(leaf[None], (Lk,) + (1,) * leaf.ndim))
                    for _ in range(n_moments):
                        state.append(jnp.zeros((Lk,) + leaf.shape, jnp.float32))
            # reorder to the kernel's per-layer layout: (pW, pB, mW, mB,
            # vW, vB) for adam, (pW, pB, velW, velB) for sgd
            half = 1 + n_moments
            flat = []
            for li in range(len(params)):
                chunk6 = state[2 * half * li : 2 * half * (li + 1)]
                Wslabs, Bslabs = chunk6[:half], chunk6[half:]
                flat.extend([Wslabs[0], Bslabs[0]])
                for j in range(1, half):
                    flat.extend([Wslabs[j], Bslabs[j]])
            state = flat
            if adaptive:
                state.append(jnp.zeros((Lk, 8, 128), jnp.float32))

            ekeys = jax.random.split(key, epochs)
            t0s = jnp.arange(epochs, dtype=jnp.int32) * n_batches

            if bs_pad != bs:
                pad_mask = jnp.asarray(
                    np.concatenate(
                        [np.ones((n_batches, bs), np.float32),
                         np.zeros((n_batches, bs_pad - bs), np.float32)], 1
                    ).reshape(-1)
                )
            else:
                pad_mask = None

            def _epoch_rows(perm):
                if bs_pad == bs:
                    return perm
                idx = perm.reshape(n_batches, bs)
                return jnp.concatenate(
                    [idx, jnp.zeros((n_batches, bs_pad - bs), idx.dtype)], 1
                ).reshape(-1)

            def _run_epoch(st, key_e, t0, lr_col):
                perm = jax.random.permutation(key_e, n)[:R]
                idx = _epoch_rows(perm)
                Wl = TWf[:, idx].T[:, lane_split]  # [Rp, Lk], lane-minor
                if pad_mask is not None:
                    Wl = Wl * pad_mask[:, None]
                return epoch_fn(
                    Xb[idx], Y[idx], Wl, lr_col, alpha,
                    t0.reshape(1, 1), st,
                ), Wl

            if not adaptive:
                def body(st, xs):
                    key_e, t0 = xs
                    if solver == "sgd" and schedule == "invscaling":
                        # sklearn t_ advances by n samples per epoch
                        e = (t0 // n_batches).astype(jnp.float32)
                        lr_col = lr / (e * n + 1.0) ** power_t
                    else:
                        lr_col = lr
                    st, _ = _run_epoch(st, key_e, t0, lr_col)
                    return st, None

                state, _ = jax.lax.scan(body, state, (ekeys, t0s))
            else:
                def body(carry, xs):
                    st, lr_col, best, wait = carry
                    key_e, t0 = xs
                    st = st[:-1] + [jnp.zeros_like(st[-1])]  # reset loss acc
                    st, Wl = _run_epoch(st, key_e, t0, lr_col)
                    data_loss = st[-1][:, 0, 0] / n_batches  # [Lk]
                    # L2 term added host-side from end-of-epoch weights
                    # (sklearn accumulates it per batch; the improvement
                    # signal only needs epoch resolution)
                    l2 = jnp.zeros((Lk,), jnp.float32)
                    for li in range(len(params)):
                        Wli = st[per_layer * li]
                        l2 = l2 + jnp.sum(
                            Wli.astype(jnp.float32) ** 2,
                            axis=tuple(range(1, Wli.ndim)),
                        )
                    bw_mean = jnp.maximum(jnp.sum(Wl, axis=0) / n_batches, 1e-12)
                    epoch_loss = data_loss + 0.5 * alpha[:, 0] * l2 / bw_mean
                    improved = epoch_loss < best - tol
                    wait = jnp.where(improved, 0, wait + 1)
                    cut = wait > no_change
                    lr_col = jnp.where(
                        cut[:, None], jnp.maximum(lr_col / 5.0, 1e-6), lr_col
                    )
                    wait = jnp.where(cut, 0, wait)
                    best = jnp.minimum(best, epoch_loss)
                    return (st, lr_col, best, wait), None

                carry0 = (
                    state, lr,  # [Lk, 1] per-lane column (mutated by cuts)
                    jnp.full((Lk,), jnp.inf, jnp.float32),
                    jnp.zeros((Lk,), jnp.int32),
                )
                (state, _, _, _), _ = jax.lax.scan(body, carry0, (ekeys, t0s))

            # ---- eval (XLA): weighted score per lane over row chunks ----
            pWs = [state[per_layer * li] for li in range(len(params))]
            pBs = [state[per_layer * li + 1][:, 0:1, :] for li in range(len(params))]
            act_f = _act(act)
            Xe = jnp.pad(Xb, ((0, n_pad - n), (0, 0)))
            EWp = jnp.pad(EW.astype(jnp.float32), ((0, 0), (0, n_pad - n)))
            if classification:
                ye = jnp.pad(y.astype(jnp.int32), (0, n_pad - n))
            else:
                ye = jnp.pad(y.astype(jnp.float32), (0, n_pad - n))

            def forward_chunk(start):
                h = jax.lax.dynamic_slice(Xe, (start, 0), (rc, d))
                out = jnp.einsum(
                    "rd,ldh->lrh", h, pWs[0].astype(mdt),
                    preferred_element_type=jnp.float32,
                ) + pBs[0]
                for li in range(1, len(params)):
                    out = jnp.einsum(
                        "lrh,lhk->lrk",
                        act_f(out).astype(mdt),
                        pWs[li].astype(mdt),
                        preferred_element_type=jnp.float32,
                    ) + pBs[li]
                ewc = jax.lax.dynamic_slice(
                    EWp, (0, start), (S, rc)
                )[lane_split]  # [Lk, rc]
                return out, ewc

            if classification:
                def ebody(acc, start):
                    out, ewc = forward_chunk(start)
                    pred = jnp.argmax(out, axis=-1)
                    yc = jax.lax.dynamic_slice(ye, (start,), (rc,))
                    hit = (pred == yc[None, :]).astype(jnp.float32)
                    return acc + jnp.sum(hit * ewc, axis=1), None

                acc, _ = jax.lax.scan(
                    ebody, jnp.zeros((Lk,), jnp.float32),
                    jnp.arange(0, n_pad, rc),
                )
                den = jnp.sum(EWp, axis=1)[lane_split]
                score = acc / jnp.maximum(den, 1e-12)
                return {"score": score[:L0].reshape(chunk, S)}

            def ebody(carry, start):
                sw, swy, swyy, ssr = carry
                out, ewc = forward_chunk(start)
                pred = out[:, :, 0]
                yc = jax.lax.dynamic_slice(ye, (start,), (rc,))[None, :]
                sw = sw + jnp.sum(ewc, axis=1)
                swy = swy + jnp.sum(ewc * yc, axis=1)
                swyy = swyy + jnp.sum(ewc * yc * yc, axis=1)
                ssr = ssr + jnp.sum(ewc * (yc - pred) ** 2, axis=1)
                return (sw, swy, swyy, ssr), None

            z = jnp.zeros((Lk,), jnp.float32)
            (sw, swy, swyy, ssr), _ = jax.lax.scan(
                ebody, (z, z, z, z), jnp.arange(0, n_pad, rc)
            )
            swc = jnp.maximum(sw, 1e-12)
            ss_tot = jnp.maximum(swyy - swy * swy / swc, 1e-12)
            r2 = 1.0 - ssr / ss_tot
            mse = ssr / swc
            return {
                "score": r2[:L0].reshape(chunk, S),
                "mse": mse[:L0].reshape(chunk, S),
            }

        return fn


class MLPClassifierKernel(_MLPBase):
    name = "MLPClassifier"
    task = "classification"

    def _out_dim(self, static):
        return max(int(static["_n_classes"]), 2)

    def _target(self, y, static):
        return jax.nn.one_hot(y, self._out_dim(static), dtype=jnp.float32)

    def _loss(self, pred, tb):
        logp = jax.nn.log_softmax(pred, axis=-1)
        return -jnp.sum(tb * logp, axis=-1)

    def predict(self, params, X, static: Dict[str, Any]):
        logits = self._forward(params, X.astype(jnp.float32), static)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static: Dict[str, Any]):
        logits = self._forward(params, X.astype(jnp.float32), static)
        return logits[:, 1] - logits[:, 0]

    def predict_proba(self, params, X, static: Dict[str, Any]):
        logits = self._forward(params, X.astype(jnp.float32), static)
        return jax.nn.softmax(logits, axis=-1)


class MLPRegressorKernel(_MLPBase):
    name = "MLPRegressor"
    task = "regression"

    def _out_dim(self, static):
        return 1

    def _target(self, y, static):
        return y.astype(jnp.float32)[:, None]

    def _loss(self, pred, tb):
        return 0.5 * jnp.sum((pred - tb) ** 2, axis=-1)

    def predict(self, params, X, static: Dict[str, Any]):
        return self._forward(params, X.astype(jnp.float32), static)[:, 0]


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(MLPClassifierKernel())
register_kernel(MLPRegressorKernel())
