"""Tree-ensemble kernels: RandomForest and GradientBoosting (clf + reg).

Capability target: the four ensemble rows of the reference whitelist
(``aws-prod/worker/worker.py:38-49``). Built on the histogram tree core
(ops/trees.py). Design notes:

- Structural hyperparameters (n_estimators, max_depth, max_features,
  n_bins) are static — they change scan lengths/shapes, so each combo is a
  compile bucket; learning_rate and subsample are traced.
- sklearn's ``max_depth=None`` (grow to purity) is capped at a static depth
  (10) — a documented approximation; unsplittable nodes pass through, so a
  shallower-than-cap tree is representable exactly. An EXPLICIT max_depth
  may go to 14 on the ensemble kernels (their chunked fits bound dispatch
  time; each level doubles histogram work).
- RF bootstrap is the exact multinomial resample (n categorical draws from
  the weight-masked rows -> per-row counts), per-node feature subsets follow
  max_features ("sqrt"/"log2"/int/float). Forest prediction averages leaf
  class distributions and argmaxes — sklearn's soft-vote semantics.
- GBT is Newton-step boosting on log-loss/squared-loss gradients (leaf
  value = sum g / sum h), with sklearn's (k-1)/k multinomial leaf scaling;
  stages run under ``lax.scan``, trees per class under ``vmap``.
- Trees bin features once per dataset (quantile bins) via the
  ``prepare_data`` hook the trial engine calls once per bucket — the
  reference re-read the CSV per subtask; we don't even re-bin.

Split scores use the unified S^2/C gain rather than sklearn's exact
friedman_mse/gini-on-sorted-values; scores match sklearn statistically
(tests assert tolerance, not bit equality) — SURVEY.md §7 flags trees as
the riskiest parity item and this is the deliberate trade.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.trees import (
    bin_data,
    build_tree,
    build_tree_deep,
    predict_tree,
    predict_tree_deep,
    quantile_bins,
)
from .base import ModelKernel

# Complete-tree caps (small data / GBT): each level doubles histogram work,
# so the level-wise complete builder stops at 10 (heuristic) / 14 (explicit
# on chunked kernels). Above _DEEP_N samples, kernels that grow to purity in
# sklearn (RF, DecisionTree — the reference's exact-CART fit,
# aws-prod/worker/worker.py:315) switch to the frontier-compacted deep
# builder (ops/trees.build_tree_deep): depth to _DEEP_LEVELS with a
# _DEEP_W-node active frontier per level, the regime where Covertype-class
# accuracy lives (sklearn RF cv ~0.95 needs depth ~25, not 10).
_DEPTH_CAP = 10
_DEPTH_HARD_CAP = 14
_DEEP_LEVELS = int(os.environ.get("CS230_DEEP_LEVELS", "24"))
#: levels past log2(n) the arena may grow (purity trees on real data run
#: well past log2(n); sweep hook for the depth-vs-time trade)
_DEEP_LEVEL_MARGIN = int(os.environ.get("CS230_DEEP_LEVEL_MARGIN", "8"))
_DEEP_LEVELS_EXPLICIT = 32
# Deep-arena defaults, re-swept on-device in r3 (RF-100, v5e, after the
# gather-free routing + s8 histogram work made width ~40% cheaper):
#   50% Covertype (sklearn cv 0.8113, 207 s):
#     W=256 nb=64  94.6 s cv 0.7894   W=384 nb=64 131.2 s cv 0.7991
#     W=512 nb=64 163.6 s cv 0.8048   W=512 nb=48 125.9 s cv 0.8040
#   100% Covertype: W=512 nb=48 225.8 s cv 0.8224 (r2 default: 320 s 0.8008)
# Frontier WIDTH is the binding capacity (deeper levels alone changed
# nothing: cv 0.7896 at levels=30); coarser 48-bin quantiles buy the wider
# frontier back at unchanged cv. The width formula itself scales with n
# (2^ceil(log2(n/64))), so this cap only binds past ~33k rows — small
# fractions keep their narrower, faster arenas. Env-tunable for sweeps.
#
# r4: per-level histogram cost is ~ W x n_bins, so width and bins TRADE at
# constant cost — and at full Covertype the trade strongly favors width.
# (The sklearn denominator was re-measured UNCONTENDED at 413.9-420.2 s /
# cv 0.8400 — the r3 613.7/672.5 s figures were CPU-contended; see
# BASELINE.md r4. First-pass times unless noted.)
#   W=768  nb=32 cv 0.8295   W=896 nb=28 cv 0.8318
#   W=1024 nb=24 cv 0.8328 (231.9 s steady = 1.80x vs honest 417 s)
#   W=1024 nb=16 cv 0.8309 (206.6 s steady = 2.02x)
#   W=1536 nb=16 cv 0.8366 (286.7 s)   W=2048 nb=12 cv 0.8365 (saturates)
# The top width band therefore pairs W=1024 with 24 bins; the narrower
# bands keep the 48-bin cap their parity anchors were measured at.
_DEEP_W = int(os.environ.get("CS230_DEEP_W", "1536"))
_DEEP_BINS_CAP = int(os.environ.get("CS230_DEEP_BINS", "48"))
#: bins cap when the TOP width bands are in play (n > 49152): the measured
#: constant-cost width/bins trade above. _DEEP_BINS_WIDEST applies at the
#: 1536-wide band (n > 80k), where the r5 Pareto sweep landed on
#: (1536, 17, 512) + adaptive 48/16: CV 0.8368 (-0.0027 vs sklearn) at
#: 200.4 s = 2.42x — the first default inside BOTH r4 #4 bars.
_DEEP_BINS_WIDE = int(os.environ.get("CS230_DEEP_BINS_WIDE", "24"))
_DEEP_BINS_WIDEST = int(os.environ.get("CS230_DEEP_BINS_WIDEST", "16"))
#: r5 adaptive bin resolution (ops/trees.build_tree_deep nb_schedule):
#: candidate evaluation runs at the full (fine) binning while the
#: candidate frontier has <= _DEEP_BINS_OCC nodes — early splits on BIG
#: nodes get fine thresholds — and at _DEEP_BINS_DEEP once wide, where the
#: frontier-width x bins product is the profiled per-level MXU cost.
#: 0 disables (single resolution everywhere).
_DEEP_BINS_OCC = int(os.environ.get("CS230_DEEP_BINS_OCC", "256"))
_DEEP_BINS_DEEP = int(os.environ.get("CS230_DEEP_BINS_DEEP", "24"))


_deep_w_force_warned: set = set()


def _warn_deep_w_force(width: int) -> None:
    if width in _deep_w_force_warned:
        return
    _deep_w_force_warned.add(width)
    from ..utils import get_logger

    get_logger().warning(
        "CS230_DEEP_W_FORCE=%d overrides the deep-arena width bands for "
        "EVERY grow-to-purity fit in this process", width,
    )


_deep_bins_warned: set = set()


def _warn_deep_bins_clamp(requested: int, cap: int) -> None:
    """Once-per-process notice that the deep arena overrides an explicitly
    requested finer n_bins (CS230_DEEP_BINS / CS230_DEEP_BINS_WIDE caps) —
    callers otherwise can't detect the divergence (ADVICE r2)."""
    if (requested, cap) in _deep_bins_warned:
        return
    _deep_bins_warned.add((requested, cap))
    from ..utils import get_logger

    get_logger().warning(
        "deep-tree arena clamps requested n_bins=%d to %d "
        "(CS230_DEEP_BINS / CS230_DEEP_BINS_WIDE; large-n grow-to-purity "
        "path only)",
        requested,
        cap,
    )


def _deep_n_threshold() -> int:
    """Sample count above which grow-to-purity kernels use the deep builder
    (env-tunable so CPU tests can exercise the deep path on small data).

    r4 re-measure at the boundary (1,162-row Covertype curve draw, RF-100):
    sklearn's CV across 8 seeds is 0.4969 +- 0.0067 (min 0.4819, the
    committed seed-42 row 0.5112 is its high tail); the complete builder's
    depth cap (min(10, ceil(log2(n)) - 2) = 9 here) lands at 0.4802 —
    BELOW sklearn's seed minimum — while the deep grow-to-purity arena
    scores 0.4914, inside 1 sigma of the seed mean, at 2.23 s steady vs
    the committed 3.17 s sklearn row (the r4 tree kernels cut the deep
    path's small-n cost ~2x from the r2-era 4.3 s that previously
    justified 4096). Raising arena width/bins beyond the small-n band
    buys nothing (W=128/nb=128 measured 0.4889): the residual delta is
    bootstrap/feature-subset RNG, not capacity. Above 1024 rows, every
    fraction of the scaling curve runs the builder whose depth semantics
    match sklearn's."""
    return int(os.environ.get("CS230_TREE_DEEP_N", "1024"))


def _resolve_max_features(spec, d: int, default) -> int:
    if spec is None:
        spec = default
    if spec in ("sqrt", "auto"):
        return max(1, int(np.sqrt(d)))
    if spec == "log2":
        return max(1, int(np.log2(max(d, 2))))
    if isinstance(spec, float) and 0 < spec <= 1:
        return max(1, int(spec * d))
    if spec in (1.0, "all"):
        return d
    return max(1, min(int(spec), d))


class _TreeBase(ModelKernel):
    #: default for max_features resolution (overridden per family)
    _mf_default: Any = 1.0

    def trace_salt(self):
        """ops/trees.py env knobs read at trace/import time that change the
        compiled program but don't land in ``static`` — they must key every
        executable cache (same hazard the SVC solver knobs hit: a knob flip
        silently reloading the pre-knob AOT blob). CS230_STREAM (resolved)
        joins them: the streamed and single-shot drivers stage different
        dataset forms under different keys."""
        from ..data.streaming import stream_mode
        from ..ops.trees import _hist_kernel_mode

        return (
            stream_mode(),
            os.environ.get("CS230_DEEP_WSCHED", ""),
            _hist_kernel_mode(),  # resolved, not raw: aliases share a key
            os.environ.get("CS230_HIST_COMPACT", "0"),
            os.environ.get("CS230_HIST_BLOCK_ROWS", ""),
            os.environ.get("CS230_HIST_BLOCK_NODES", ""),
            os.environ.get("CS230_COARSE_BINS", ""),
            os.environ.get("CS230_TREE_GROUP_MB", ""),
            os.environ.get("CS230_DEEP_NBSCHED", ""),
            os.environ.get("CS230_DEEP_BINS_OCC", ""),
            os.environ.get("CS230_DEEP_BINS_DEEP", ""),
        )
    #: sklearn semantics grow this family to purity (RF/DecisionTree) —
    #: eligible for the deep frontier-compacted builder on large data
    _supports_deep = False

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        n_bins = int(static.get("n_bins", 128))
        n_bins = min(n_bins, max(8, n))
        depth = static.get("max_depth")
        # explicit depths past the complete-builder's cap route to the deep
        # arena; the cap is kernel-dependent (chunked ensembles honor up to
        # _DEPTH_HARD_CAP complete levels, plain DT only _DEPTH_CAP), so the
        # honored depth stays monotonic in the requested depth
        _complete_cap = (
            _DEPTH_HARD_CAP if hasattr(self, "chunked_plan") else _DEPTH_CAP
        )
        deep = (
            self._supports_deep
            and n > _deep_n_threshold()
            and (depth is None or int(depth) > _complete_cap)
        )
        if deep:
            grow_to_purity = depth is None
            if grow_to_purity:
                levels = min(
                    _DEEP_LEVELS,
                    int(np.ceil(np.log2(max(n, 8)))) + _DEEP_LEVEL_MARGIN,
                )
            else:
                levels = min(int(depth), _DEEP_LEVELS_EXPLICIT)
            # Width by explicit monotone bands anchored at on-device
            # parity measurements, r5 re-anchored under adaptive bins
            # (Covertype RF-100, CV delta vs sklearn in parens):
            # 5.8k->128 (+0.007 BEATS, 3.6x), 11.6k->128 (-0.006, 6.3 s
            # = 5.2x), 29k->256 (-0.007, 3.8x), 58k->1024 (+0.0002
            # BEATS, 127.8 s), 116k->1536+(1536,17,512)+deep16 (-0.0027,
            # 200.4 s = 2.42x — BASELINE.md r5 sweep table). Band edges
            # sit between measured points, so every n gets the narrowest
            # width whose band endpoints sat inside the 0.01 parity band;
            # the smallest deep fits (n just over the 1024 threshold)
            # keep 64-wide arenas.
            bins_cap = _DEEP_BINS_CAP
            force_w = os.environ.get("CS230_DEEP_W_FORCE")
            if force_w:
                # sweep/parity hook: bypass the width bands entirely (the
                # BASELINE.md full-scale Pareto knob). Applies to EVERY
                # deep fit while set — warn once so a forgotten export
                # doesn't silently inflate small fits 12x.
                try:
                    width = int(force_w)
                    if width < 64:
                        raise ValueError(force_w)
                except ValueError:
                    raise ValueError(
                        f"CS230_DEEP_W_FORCE={force_w!r}: expected an "
                        "integer arena width >= 64"
                    ) from None
                _warn_deep_w_force(width)
            else:
                if n <= 5000:
                    width = 64
                elif n <= 24576:
                    width = 128
                elif n <= 49152:
                    width = 256
                elif n <= 80_000:
                    # the 58k (50%) parity point BEATS sklearn at 1024
                    # (0.8121 vs 0.8113, r4) — keep its measured band
                    width = 1024
                else:
                    # r5 Pareto: 1536 through the critical mid levels with
                    # a 512 tail and 48/16 adaptive bins (sweep table in
                    # BASELINE.md r5) — CV -0.0027 at 2.42x
                    width = 1536
                width = min(_DEEP_W, width)
                if width >= 1024:
                    # top bands: trade bins for width at constant histogram
                    # cost (W x n_bins) — measured strictly better CV. Only
                    # when the wide arena is actually in play (a user pinning
                    # CS230_DEEP_W to a narrower arena keeps the 48-bin cap
                    # its parity points were measured at).
                    bins_cap = min(
                        bins_cap,
                        _DEEP_BINS_WIDEST if width >= 1536 else _DEEP_BINS_WIDE,
                    )
            depth = levels
            # coarser quantile bins in the deep arena (see sweep table at
            # _DEEP_W): ~1.5x faster histograms AND better CV than 128 —
            # like the depth caps, this deliberately overrides a finer
            # user-requested binning for the deep path only.
            #
            # r5: BINNING stays at the fine cap (_DEEP_BINS_CAP, 48);
            # bins_cap (24 at the wide band) becomes the DEEP-level
            # resolution of the adaptive nb_schedule instead of a global
            # clamp — early/narrow-frontier candidates keep the fine
            # thresholds (big-node splits are where resolution buys CV),
            # wide frontiers pay only the coarse bin axis.
            fine_cap = max(_DEEP_BINS_CAP, bins_cap)
            eff_fine = min(n_bins, fine_cap)
            deep_nb = min(eff_fine, min(bins_cap, _DEEP_BINS_DEEP))
            nb_occ = _DEEP_BINS_OCC
            if os.environ.get("CS230_DEEP_BINS_OCC") is None and width == 256:
                # the 256-wide band needs its LAST pre-saturation level
                # (W_l=128, candidates=256) fine too: 25% Covertype
                # measured occ 256 -> CV -0.0104 (outside the band) vs
                # occ 384 -> -0.0065 at 22.6 s (3.8x). Applied only when
                # the knob is at its default.
                nb_occ = 384
            sched_ok = (
                nb_occ > 0
                and deep_nb < eff_fine
                and eff_fine % deep_nb == 0
            )
            # warn against the cap that will ACTUALLY apply: the fine cap
            # when the adaptive schedule engages, the flat deep cap when it
            # does not (disabled/non-dividing resolutions)
            cap_used = fine_cap if sched_ok else bins_cap
            if "n_bins" in static and n_bins > cap_used:
                _warn_deep_bins_clamp(n_bins, cap_used)
            n_bins = min(n_bins, cap_used)
            nb_sched = (nb_occ, deep_nb) if sched_ok else None
        elif depth is None:
            # small data: the complete-tree builder to ~log2(n) levels is
            # already near-purity and cheaper to compile than the arena
            depth = min(_DEPTH_CAP, max(3, int(np.ceil(np.log2(max(n, 8)))) - 2))
        else:
            # deep explicit requests are only safe for kernels whose fits
            # chunk across dispatches; plain DecisionTree (no chunked
            # protocol) keeps the uniform cap
            hard = _DEPTH_HARD_CAP if hasattr(self, "chunked_plan") else _DEPTH_CAP
            depth = min(int(depth), hard)
        mf = _resolve_max_features(static.get("max_features"), d, self._mf_default)
        msl = static.get("min_samples_leaf", 1)
        if isinstance(msl, float) and msl < 1:
            msl = max(1, int(msl * n))
        out = {
            **static,
            "_depth": depth,
            "_n_bins": n_bins,
            "_mf": mf,
            "_msl": float(msl),
            "_seed": int(static.get("random_state") or 0),
        }
        if deep:
            out["_deep"] = True
            out["_levels"] = levels
            out["_W"] = width
            if nb_sched is not None:
                out["_nb_sched"] = nb_sched
            if width >= 1536 and n > 80_000 and grow_to_purity and not force_w:
                # r5 top band: one extra wide level, then a hard 512 tail —
                # the measured Pareto point (200.4 s, CV 0.8368); the
                # formula-tail (width//2 = 768) costs ~10% more for no
                # measured CV
                out["_wsched"] = (width, 17, 512)
            elif width >= 1024 and n > 80_000 and grow_to_purity and not force_w:
                # decaying width schedule at full scale: per-level cost is
                # linear in frontier width and the deepest levels split
                # mostly-pure low-gain nodes. Measured on full Covertype
                # RF-100 (sklearn 417 s / cv 0.8400): no schedule 231.9 s
                # cv 0.8328; (1024,16,512) 175.8 s = 2.37x at cv 0.8311
                # (-0.0089, inside the 0.01 band); (1024,12,512) is the
                # over-pruned point (146.6 s but cv 0.8236). Gated to the
                # grow-to-purity path (where it was validated — a user's
                # EXPLICIT max_depth keeps the exact requested width) and
                # to n > 80k so the 58k band point keeps its measured
                # margin.
                out["_wsched"] = (width, 16, width // 2)
        return out

    def memory_estimate_mb(self, n: int, d: int, static: Dict[str, Any]) -> float:
        """Depth-aware: the dominant working set is the deepest level's
        histogram [2^(depth-1) nodes, d, bins, k+1] (x3 for H/H_prev/stack
        buffers) plus the binned dataset — 16x growth from depth 10 to 14
        must throttle trials-per-dispatch accordingly. Deep (arena) mode is
        frontier-bounded instead: ~4 histogram-sized buffers of W rows
        (H, left+right candidates, gathered next-H).

        The complete builder's gather-free routing/leaf forms
        (ops/trees._route_left/_leaf_sums/_leaf_select) additionally
        materialize [n, m] one-hot/compare buffers over the FULL row count
        (m = min(2^level, _LOOKUP_M) columns, several f32/bool operands
        live at once, not row-chunked) — at large n these dominate the
        histogram term and must count toward the dispatch throttle. The
        deep arena routes by O(n) gathers, so only the histogram and
        dataset terms apply there."""
        from ..ops.trees import _LOOKUP_M

        n_bins = int(static.get("_n_bins", 128))
        kk = max(int(static.get("_n_classes", 2)), 2) + 1
        route = 0.0
        if static.get("_deep"):
            W = int(static["_W"])
            hist = 4.0 * W * d * n_bins * kk * 4
        else:
            depth = int(static.get("_depth", 8))
            hist = 3.0 * (2 ** max(depth - 1, 0)) * d * n_bins * kk * 4
            # routing compare mask [n, m] (f32 cols + 2 bool masks ~6 B) and
            # the [n, n_leaves] f32 leaf-sum one-hot (~4 B), m capped at
            # _LOOKUP_M past which the O(n) gather path takes over
            m_route = min(2 ** max(depth - 1, 0), _LOOKUP_M)
            # leaf-sum one-hot only exists when n_leaves fits the lookup
            # form; past _LOOKUP_M the builder switches to segment_sum
            m_leaf = 2**depth if 2**depth <= _LOOKUP_M else 0
            route = 6.0 * n * m_route + 4.0 * n * m_leaf
        # forest kernels fit T trees concurrently (_tree_group_size): their
        # per-tree buffers coexist, so the engine's lane throttle must see
        # the multiplied working set
        group = (
            self._tree_group_size(n, d, static)
            if hasattr(self, "_tree_group_size") else 1
        )
        return max(1.0, (group * (hist + route) + 4.0 * n * d * 2) / 1e6)

    @staticmethod
    def _hist_cols(static, d, prepared=None):
        """Effective bin-column total of the level histogram: d * n_bins
        ungrouped, or the grouped sum d_cont*n_bins + d_coarse*COARSE_BINS
        when prepare_data staged feature groups."""
        from ..ops.trees import COARSE_BINS

        n_bins = int(static.get("_n_bins", 128))
        sched = static.get("_nb_sched")
        if sched:
            # adaptive resolution: the wide (deep) levels dominate the
            # MAC-weighted level sum, so cost at the deep resolution
            n_bins = int(sched[1])
        if (
            prepared is not None
            and isinstance(prepared, dict)
            and "xb_coarse" in prepared
        ):
            d_b = prepared["xb_coarse"].shape[1]
            return (d - d_b) * n_bins + d_b * COARSE_BINS
        return d * n_bins

    def macs_estimate(self, n, d, static, prepared=None):
        """Histogram-contraction MACs of one (trial, split) fit — used for
        host-vs-accelerator placement, chunk planning, and the harnesses'
        MFU accounting. ``prepared`` (the prepare_data dict, when the caller
        has it) prices grouped histograms at their true bin total instead of
        d*n_bins — a ~3x overcharge on one-hot-heavy data like Covertype
        that would otherwise schedule ~3x too many chunk dispatches."""
        kk = (
            max(int(static.get("_n_classes", 2)), 2) + 1
            if self.task == "classification"
            else 2
        )
        cols = self._hist_cols(static, d, prepared)
        trees = int(static.get("n_estimators", 1))
        if static.get("_deep"):
            W = int(static["_W"])
            levels = int(static["_levels"])
            ramp = int(np.log2(W))
            sched = static.get("_wsched")
            if sched:
                # width-scheduled arena: hi-width levels then lo-width tail
                hi, split, lo = (int(x) for x in sched)
                w_sum = (
                    max(min(split, levels) - ramp + 2, 2) * hi
                    + max(levels - split, 0) * lo
                )
            else:
                w_sum = max(levels - ramp + 2, 2) * W
            per_tree = float(n) * kk * cols * w_sum
        else:
            depth = int(static.get("_depth", 8))
            per_tree = float(n) * (2 ** max(depth - 1, 0)) * kk * cols
        return trees * per_tree

    def _fit_one_tree(self, X, S, C, static, key, precision):
        """Dispatch to the complete-tree or deep arena builder. ``X`` is the
        prepared-data dict (or a bare binned matrix); the deep builder
        additionally receives the feature-grouped histogram arrays when
        prepare_data staged them."""
        xb = X["xb"] if isinstance(X, dict) else X
        common = dict(
            n_bins=static["_n_bins"],
            min_samples_leaf=static["_msl"],
            max_features=static["_mf"] if static["_mf"] < xb.shape[1] else None,
            key=key,
            precision=precision,
            # classification stats are one_hot(y)*w columns that sum to the
            # count column exactly — derive it from the class histograms
            # instead of contracting an extra MXU row per node
            count_from_stats=self.task == "classification",
        )
        if static.get("_deep"):
            groups = None
            if isinstance(X, dict) and "xb_coarse" in X:
                groups = {kk: X[kk] for kk in
                          ("xb_cont", "xb_coarse", "fid_cont", "fid_coarse")}
            return build_tree_deep(
                xb, S, C, levels=static["_levels"], width=static["_W"],
                groups=groups, w_schedule=static.get("_wsched"),
                nb_schedule=static.get("_nb_sched"), **common
            )
        return build_tree(xb, S, C, depth=static["_depth"], **common)

    def _tree_predict(self, xq, tree, static):
        if static.get("_deep"):
            return predict_tree_deep(
                xq, tree, static["_levels"], static["_n_bins"]
            )
        return predict_tree(xq, tree, static["_depth"], static["_n_bins"])

    # trial-engine hook: bin once per bucket, share across trials/splits
    def prepare_data(self, X: np.ndarray, static: Dict[str, Any]):
        from ..ops.trees import COARSE_BINS

        edges = quantile_bins(np.asarray(X), static["_n_bins"])
        xb = np.asarray(bin_data(X, edges))
        out = {"X": np.asarray(X, np.float32), "xb": xb, "edges": edges}
        if static.get("_deep"):
            # feature-grouped histograms: low-cardinality columns (one-hot/
            # binary — quantile dedup gives them <= COARSE_BINS codes) go to
            # a narrow-bin group; per-level cost is linear in the bin total,
            # so this is ~3x fewer histogram MACs on Covertype (44/54
            # columns are binary) at an identical split-candidate set
            n_codes = 1 + np.isfinite(edges).sum(axis=1)
            coarse = n_codes <= COARSE_BINS
            if coarse.sum() >= 8 and (~coarse).sum() >= 1:
                fid_cont = np.where(~coarse)[0].astype(np.int32)
                fid_coarse = np.where(coarse)[0].astype(np.int32)
                out.update(
                    xb_cont=np.ascontiguousarray(xb[:, fid_cont]),
                    xb_coarse=np.ascontiguousarray(xb[:, fid_coarse]),
                    fid_cont=fid_cont,
                    fid_coarse=fid_coarse,
                )
        return out

    @staticmethod
    def _query_bins(params, X, static):
        """Accept either prepared data (dict with precomputed bins) or a raw
        feature matrix (artifact-inference path: bin via stored edges)."""
        if isinstance(X, dict):
            return X["xb"]
        return bin_data(X, params["edges"])

    # random_state seeds the forest/boosting PRNG: keep it (override the
    # base class's blanket ignore)
    ignored_params = ModelKernel.ignored_params - {"random_state"}


def _bootstrap_counts(key, w, n):
    """Exact bootstrap: n draws with replacement from rows where w>0.

    Uniform-over-active-rows multinomial via inverse-CDF searchsorted —
    O(n log n), unlike jax.random.categorical whose gumbel matrix is
    [draws, categories] = n x n (54 GB at Covertype scale).

    Counts are capped at 127 so classification histograms can ride the s8
    MXU path (ops/trees integer_stats). The cap is unreachable in
    practice: P(one specific row drawn >=128 times in n uniform draws)
    <= C(n,128)/n^128 < 1/128! ~ 1e-216 for any n the deep path sees."""
    active = (w > 0).astype(jnp.int32)
    caw = jnp.cumsum(active)
    n_active = caw[-1]
    targets = jax.random.randint(key, (n,), 1, jnp.maximum(n_active, 1) + 1)
    rows = jnp.searchsorted(caw, targets, side="left")
    counts = jax.ops.segment_sum(
        jnp.ones((n,), jnp.float32), rows, num_segments=n
    )
    return jnp.minimum(counts, 127.0)


class _RandomForestBase(_TreeBase):
    _supports_deep = True  # sklearn RF default grows each tree to purity
    static_defaults = {
        "n_estimators": 100,
        "max_depth": None,
        "min_samples_leaf": 1,
        "min_samples_split": 2,
        "max_features": None,
        "bootstrap": True,
        "random_state": 0,
        "n_bins": 128,
        "criterion": "default",
        "min_weight_fraction_leaf": 0.0,
        "max_leaf_nodes": None,
        "min_impurity_decrease": 0.0,
        "oob_score": False,
        "ccp_alpha": 0.0,
        "max_samples": None,
        "monotonic_cst": None,
    }

    def _one_tree(self, X, S, C, static, key):
        boot_key, feat_key = jax.random.split(key)
        if static.get("bootstrap", True):
            counts = _bootstrap_counts(boot_key, C, S.shape[0])
        else:
            counts = (C > 0).astype(jnp.float32)
        return self._fit_one_tree(
            X,
            S * counts[:, None],
            C * counts,
            static,
            feat_key,
            # classification stats are small-integer counts x 0/1 one-hots —
            # exact in bf16, so the fast MXU path loses nothing; regression
            # stats are continuous y*w sums and need full f32
            (
                jax.lax.Precision.DEFAULT
                if self.task == "classification"
                else jax.lax.Precision.HIGHEST
            ),
        )

    def _tree_group_size(self, n: int, d: int, static: Dict[str, Any]) -> int:
        """Trees fitted CONCURRENTLY per sequential step (an inner vmap
        inside the tree loop). At small n the per-level ops are latency-
        bound, not bandwidth-bound — profiled on-device: lax.top_k cost is
        FLAT in the vmapped lane count, and the histogram's marginal
        per-lane cost is ~60% of its solo cost — so running trees one at a
        time wastes most of each level's fixed cost. The group is sized by
        a per-lane memory budget: at full-Covertype shapes (W=1024) the
        candidate-histogram buffers alone are GBs and T collapses to 1,
        which is also the bandwidth-bound regime where batching stops
        paying. Keys stay fold_in(t), so grouped, sequential, and chunked
        fits of one config produce bit-identical trees."""
        kk = (
            max(int(static.get("_n_classes", 2)), 2) + 1
            if self.task == "classification"
            else 2
        )
        n_bins = int(static.get("_n_bins", 128))
        if static.get("_deep"):
            W = int(static["_W"])
            route_w = W
        else:
            from ..ops.trees import _LOOKUP_M

            W = 2 ** max(int(static.get("_depth", 8)) - 1, 1)
            route_w = min(W, _LOOKUP_M)
        # per-tree working set: ~4 live candidate-histogram buffers
        # [2W, d, nb, kk] f32 + the [n, W] routing masks (~6 B/elem) +
        # per-row stat/leaf vectors
        per_tree_mb = (
            4.0 * 2 * W * d * n_bins * kk * 4
            + 6.0 * n * route_w
            + 16.0 * n * kk
        ) / 1e6
        # DEFAULT 64 MB => T=1 at every realistic shape: tree batching is a
        # MEASURED NEGATIVE on the tunneled v5e (10% Covertype RF-100
        # steady: T=1 10.0 s, T=2 11.6 s, T=5 13.4 s — the batched levels'
        # histogram working set multiplies while none of the level ops turn
        # out to be latency-bound enough to amortize). The knob stays for
        # hardware where the trade differs; it keys trace_salt.
        budget = float(os.environ.get("CS230_TREE_GROUP_MB", 64))
        return int(max(1, min(8, budget / max(per_tree_mb, 1.0))))

    def _fit_forest(self, X, S, C, static):
        n_trees = int(static.get("n_estimators", 100))
        base_key = jax.random.PRNGKey(static["_seed"])
        xb = X["xb"] if isinstance(X, dict) else X
        T = self._tree_group_size(xb.shape[0], xb.shape[1], static)
        G = -(-n_trees // T)
        # per-tree keys via fold_in(t) — the SAME stream the chunked paths
        # use, so monolithic and chunked fits of one config are identical.
        # Padding trees (t >= n_trees) are fitted and sliced off (<= T-1
        # wasted fits per forest).
        keys = jax.vmap(lambda t: jax.random.fold_in(base_key, t))(
            jnp.arange(G * T)
        )
        fit_group = jax.vmap(lambda k: self._one_tree(X, S, C, static, k))
        out = jax.lax.map(
            fit_group, jax.tree_util.tree_map(
                lambda a: a.reshape(G, T, *a.shape[1:]), keys
            )
        )
        return jax.tree_util.tree_map(
            lambda a: a.reshape(G * T, *a.shape[2:])[:n_trees], out
        )

    # ---- chunked-fit protocol (parallel/trial_map.py chunked path) ----
    # A forest fit on a large dataset is one long sequential device program
    # (lax.map over trees); splitting the trees across several dispatches
    # bounds single-dispatch device time (remote-device RPC deadlines) and
    # lets full-depth trees run at any dataset size. Trees are independent,
    # so the cross-dispatch state is just the running sum of per-tree leaf
    # predictions for every row; eval finalizes the soft-vote mean.

    def chunked_plan(self, static, n, d, n_classes, n_splits, prepared=None):
        chunk_macs = float(os.environ.get("CS230_TREE_CHUNK_MACS", 4e13))
        trees = int(static.get("n_estimators", 100))
        # single source of truth for the histogram MAC formulas (complete
        # and deep-arena): the same estimate drives host placement and MFU
        macs = float(max(n_splits, 1)) * self.macs_estimate(n, d, static, prepared)
        n_chunks = int(np.ceil(macs / chunk_macs))
        if n_chunks <= 1:
            return None
        trees_per_chunk = int(np.ceil(trees / n_chunks))
        return {"n_chunks": int(np.ceil(trees / trees_per_chunk)),
                "trees_per_chunk": trees_per_chunk}

    def _stat_matrix(self, y, w, static):
        if self.task == "classification":
            c = max(int(static["_n_classes"]), 2)
            return jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None], c
        return (y.astype(jnp.float32) * w)[:, None], 1

    def chunk_init(self, X, y, w, hyper, static):
        xb = X["xb"] if isinstance(X, dict) else X
        _, k = self._stat_matrix(y, w, static)
        return jnp.zeros((xb.shape[0], k), jnp.float32)

    def chunk_step(self, X, y, w, hyper, static, chunk_idx, state, plan):
        xb = X["xb"] if isinstance(X, dict) else X
        S, _ = self._stat_matrix(y, w.astype(jnp.float32), static)
        C = w.astype(jnp.float32)
        n_trees = int(static.get("n_estimators", 100))
        g = plan["trees_per_chunk"]
        base_key = jax.random.PRNGKey(static["_seed"])
        T = self._tree_group_size(xb.shape[0], xb.shape[1], static)
        G = -(-g // T)

        def one_group(carry, gi):
            i = gi * T + jnp.arange(T)
            t = chunk_idx * g + i
            keys = jax.vmap(lambda tt: jax.random.fold_in(base_key, tt))(t)
            trees = jax.vmap(
                lambda k: self._one_tree(X, S, C, static, k)
            )(keys)
            vals = jax.vmap(
                lambda tr: self._tree_predict(xb, tr, static)
            )(trees)  # [T, n, k]
            # i < g guards group padding (those ids belong to the NEXT
            # chunk, which will fit them itself — adding here would double
            # count); t < n_trees guards the final chunk's tail
            live = ((i < g) & (t < n_trees)).astype(jnp.float32)
            return carry + jnp.sum(live[:, None, None] * vals, axis=0), None

        state, _ = jax.lax.scan(one_group, state, jnp.arange(G))
        return state

    def chunk_eval(self, X, y, w_eval, hyper, static, state):
        from ..ops.metrics import (
            classification_score,
            margin_score,
            proba_score,
            regression_score,
            scoring_needs_margin,
            scoring_needs_proba,
            weighted_mse,
        )

        scoring = static.get("_scoring")
        n_trees = int(static.get("n_estimators", 100))
        mean = state / float(n_trees)
        if self.task == "classification":
            if scoring_needs_margin(scoring):
                return {"score": margin_score(scoring, y, mean[:, 1] - mean[:, 0], w_eval)}
            if scoring_needs_proba(scoring):
                proba = mean / jnp.maximum(
                    jnp.sum(mean, axis=-1, keepdims=True), 1e-12
                )
                return {"score": proba_score(
                    scoring, y, proba, w_eval, static.get("_n_classes", 2))}
            pred = jnp.argmax(mean, axis=-1).astype(jnp.int32)
            return {"score": classification_score(
                scoring, y, pred, w_eval, static.get("_n_classes", 2))}
        pred = mean[:, 0]
        return {
            "score": regression_score(scoring, y, pred, w_eval),
            "mse": weighted_mse(y, pred, w_eval),
        }

    # artifact materialization (trial_map.fit_single chunked branch)
    def fit_chunk(self, X, y, w, hyper, static, chunk_idx, carry, plan):
        w = w.astype(jnp.float32)
        S, _ = self._stat_matrix(y, w, static)
        g = plan["trees_per_chunk"]
        base_key = jax.random.PRNGKey(static["_seed"])
        xb = X["xb"] if isinstance(X, dict) else X
        T = self._tree_group_size(xb.shape[0], xb.shape[1], static)
        G = -(-g // T)
        idx = chunk_idx * g + jnp.arange(G * T)
        keys = jax.vmap(lambda t: jax.random.fold_in(base_key, t))(idx)
        trees = jax.lax.map(
            jax.vmap(lambda k: self._one_tree(X, S, w, static, k)),
            jax.tree_util.tree_map(
                lambda a: a.reshape(G, T, *a.shape[1:]), keys
            ),
        )
        trees = jax.tree_util.tree_map(
            lambda a: a.reshape(G * T, *a.shape[2:])[:g], trees
        )
        return carry, trees

    def assemble_artifact(self, trees, X, hyper, static, data_y, data_w):
        params = {"trees": trees}
        if isinstance(X, dict):
            params["edges"] = X["edges"]
        return params

    # ---- out-of-core row-block streaming (data/streaming.py) ----

    def stream_applicable(self, static: Dict[str, Any], n: int, d: int) -> bool:
        """Complete-tree classification forests only. The deep arena's
        frontier compaction keeps [n, W] routing masks resident and
        re-bins per level — not block-accumulable; regression float
        stats would trade the bitwise histogram guarantee for f32-order
        drift in the SPLITS themselves (not just the scores), so those
        families fall back to single-shot (or chunked) staging."""
        return (
            not static.get("_deep")
            and self.task == "classification"
            and int(static.get("_depth", 0)) >= 1
        )

    def stream_form(self, X_np, static: Dict[str, Any]):
        """Blocks are sliced from the prepared bin-code matrix (the only
        per-row array the builder reads); edges/X stay host-side."""
        xb = X_np["xb"] if isinstance(X_np, dict) else np.asarray(X_np)
        return np.ascontiguousarray(xb), ("xb", int(static["_n_bins"]))

    def stream_scores(self, streamer, y_pad, TW, EW, hyper_batch, static, n):
        """Block-accumulated forest fit + soft-vote accuracy over a
        RowBlockStreamer: (depth + 1) passes per tree via
        ops/trees.build_tree_streamed, which is BITWISE build_tree for
        these integer-stat histograms — per-tree splits and leaf values
        are identical to the single-shot path, per-tree keys stay
        ``fold_in(t)``, and bootstrap counts are drawn on the UNPADDED
        row range so the multinomial stream matches exactly. Prediction
        for the fitting rows reuses the builder's final node ids — a
        resident leaf lookup, no extra pass."""
        from ..data.streaming import decode_block
        from ..ops.trees import _LOOKUP_M, _leaf_select, build_tree_streamed

        c = max(int(static["_n_classes"]), 2)
        n_splits = int(TW.shape[0])
        n_pad = int(TW.shape[1])
        d = int(streamer.row_shape[0])
        depth = int(static["_depth"])
        n_bins = int(static["_n_bins"])
        mf = static["_mf"] if static["_mf"] < d else None
        n_trees = int(static.get("n_estimators", 100))
        base_key = jax.random.PRNGKey(static["_seed"])
        n_internal = 2**depth - 1
        n_leaves = 2**depth

        def stream_pass(fn, carry, *consts):
            for _i, start, blk in streamer.iter_blocks():
                carry = fn(
                    carry, *consts, decode_block(blk),
                    jnp.asarray(start, jnp.int32),
                )
            return carry

        y1 = jax.nn.one_hot(y_pad, c, dtype=jnp.float32)       # [n_pad, c]
        pad_zeros = jnp.zeros((n_pad - int(n),), jnp.float32)
        scores = np.zeros((n_splits,), np.float32)
        for s in range(n_splits):
            w = TW[s].astype(jnp.float32)
            Sw = y1 * w[:, None]
            acc = jnp.zeros((n_pad, c), jnp.float32)
            for t in range(n_trees):
                key = jax.random.fold_in(base_key, t)
                boot_key, feat_key = jax.random.split(key)
                if static.get("bootstrap", True):
                    counts = jnp.concatenate(
                        [_bootstrap_counts(boot_key, w[: int(n)], int(n)),
                         pad_zeros]
                    )
                else:
                    counts = (w > 0).astype(jnp.float32)
                tree, node = build_tree_streamed(
                    stream_pass,
                    Sw * counts[:, None],
                    w * counts,
                    d,
                    depth=depth,
                    n_bins=n_bins,
                    min_samples_leaf=static["_msl"],
                    max_features=mf,
                    key=feat_key,
                    precision=jax.lax.Precision.DEFAULT,
                    count_from_stats=True,
                )
                leaf_local = node - n_internal
                if n_leaves <= _LOOKUP_M:
                    vals = _leaf_select(leaf_local, tree["leaf_val"], n_leaves)
                else:
                    vals = tree["leaf_val"][leaf_local]
                acc = acc + vals
            mean = acc / float(n_trees)
            pred = jnp.argmax(mean, axis=-1).astype(jnp.int32)
            ew = EW[s].astype(jnp.float32)
            num = jnp.sum((pred == y_pad).astype(jnp.float32) * ew)
            scores[s] = float(num / jnp.maximum(jnp.sum(ew), 1e-12))
        # trials in one bucket share an identical static config (RF hypers
        # are static), so every trial of the chunk gets the same row
        n_t = len(next(iter(hyper_batch.values()))) if hyper_batch else 1
        return np.broadcast_to(scores, (max(int(n_t), 1), n_splits)).copy()

    def _forest_leaf_mean(self, params, xq, static):
        trees = params["trees"]
        n_trees = jax.tree_util.tree_leaves(trees)[0].shape[0]
        T = max(1, min(
            self._tree_group_size(xq.shape[0], xq.shape[1], static), n_trees
        ))
        G = -(-n_trees // T)

        def one(tree):
            return self._tree_predict(xq, tree, static)

        # wrap-around padding to G*T (pad can exceed n_trees for tiny
        # forests, so slice-padding is NOT enough); padded predictions are
        # sliced off before the mean
        idx = jnp.arange(G * T) % n_trees
        grouped = jax.tree_util.tree_map(
            lambda a: jnp.take(a, idx, axis=0).reshape(G, T, *a.shape[1:]),
            trees,
        )
        vals = jax.lax.map(jax.vmap(one), grouped)  # [G, T, nq, k]
        vals = vals.reshape(G * T, *vals.shape[2:])[:n_trees]
        return jnp.mean(vals, axis=0)


class RandomForestClassifierKernel(_RandomForestBase):
    name = "RandomForestClassifier"
    task = "classification"
    _mf_default = "sqrt"

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        w = w.astype(jnp.float32)
        S = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]
        trees = self._fit_forest(X, S, w, static)
        return self.assemble_artifact(trees, X, hyper, static, y, w)

    def predict(self, params, X, static: Dict[str, Any]):
        xq = self._query_bins(params, X, static)
        proba = self._forest_leaf_mean(params, xq, static)
        return jnp.argmax(proba, axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static: Dict[str, Any]):
        """Binary margin = p(class 1) - p(class 0): monotone in the positive
        class probability, so rank metrics (roc_auc) match sklearn's
        predict_proba[:, 1] ranking."""
        xq = self._query_bins(params, X, static)
        proba = self._forest_leaf_mean(params, xq, static)
        return proba[:, 1] - proba[:, 0]

    def predict_proba(self, params, X, static: Dict[str, Any]):
        """Soft-vote mean of per-tree leaf class distributions (sklearn
        forest predict_proba semantics)."""
        xq = self._query_bins(params, X, static)
        proba = self._forest_leaf_mean(params, xq, static)
        return proba / jnp.maximum(jnp.sum(proba, axis=-1, keepdims=True), 1e-12)


class RandomForestRegressorKernel(_RandomForestBase):
    name = "RandomForestRegressor"
    task = "regression"
    _mf_default = 1.0

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        w = w.astype(jnp.float32)
        S = (y.astype(jnp.float32) * w)[:, None]
        trees = self._fit_forest(X, S, w, static)
        return self.assemble_artifact(trees, X, hyper, static, y, w)

    def predict(self, params, X, static: Dict[str, Any]):
        xq = self._query_bins(params, X, static)
        return self._forest_leaf_mean(params, xq, static)[:, 0]


class _GradientBoostingBase(_TreeBase):
    """Boosting stages are sequential, so the chunked-fit state is the
    raw-score vector F carried across dispatches (chunk_step advances g
    stages; chunk_eval scores directly from F — no trees needed for the
    trial-search path). Subclasses provide ``_prior``/``_f0``/``_stage``."""

    def chunked_plan(self, static, n, d, n_classes, n_splits, prepared=None):
        chunk_macs = float(os.environ.get("CS230_TREE_CHUNK_MACS", 4e13))
        stages = int(static.get("n_estimators", 100))
        # Tiny node*kk contraction dims at the default depth 3 underfill the
        # MXU; the classifier additionally runs bf16 histograms (~1.6x
        # faster than the regressor's full-precision ones), so the weight
        # that keeps each dispatch's wall time in the RF-chunk envelope is
        # task-dependent. The raw MAC count is macs_estimate (also used for
        # host placement and MFU accounting).
        weight = 6.0 if self.task == "classification" else 10.0
        macs = weight * float(max(n_splits, 1)) * self.macs_estimate(n, d, static)
        n_chunks = int(np.ceil(macs / chunk_macs))
        if n_chunks <= 1:
            return None
        per_chunk = int(np.ceil(stages / n_chunks))
        return {"n_chunks": int(np.ceil(stages / per_chunk)),
                "trees_per_chunk": per_chunk}

    def macs_estimate(self, n, d, static):
        """Per-stage (grad, hess) histogram trees: k_eff trees of kk=2."""
        stages = int(static.get("n_estimators", 100))
        nc = max(int(static.get("_n_classes", 2)), 2)
        k_eff = nc if (self.task == "classification" and nc > 2) else 1
        depth = int(static.get("_depth", 3))
        n_bins = int(static.get("_n_bins", 128))
        return float(stages) * k_eff * n * (2 ** max(depth - 1, 0)) * 2 * d * n_bins

    def chunk_init(self, X, y, w, hyper, static):
        xb = X["xb"] if isinstance(X, dict) else X
        w = w.astype(jnp.float32)
        return self._f0(xb.shape[0], self._prior(y, w, static), static)

    def chunk_step(self, X, y, w, hyper, static, chunk_idx, state, plan):
        # same stage loop as fit_chunk; XLA dead-code-eliminates the
        # unused stacked trees under jit
        state, _ = self.fit_chunk(X, y, w, hyper, static, chunk_idx, state, plan)
        return state

    def chunk_eval(self, X, y, w_eval, hyper, static, state):
        from ..ops.metrics import (
            classification_score,
            margin_score,
            proba_score,
            regression_score,
            scoring_needs_margin,
            scoring_needs_proba,
            weighted_mse,
        )

        scoring = static.get("_scoring")
        if self.task == "classification":
            if scoring_needs_margin(scoring):
                # binary F keeps column 0 at zero, so the logit difference
                # is just F[:, 1] - F[:, 0]
                return {"score": margin_score(
                    scoring, y, state[:, 1] - state[:, 0], w_eval)}
            if scoring_needs_proba(scoring):
                return {"score": proba_score(
                    scoring, y, jax.nn.softmax(state, axis=-1), w_eval,
                    static.get("_n_classes", 2))}
            pred = jnp.argmax(state, axis=-1).astype(jnp.int32)
            return {"score": classification_score(
                scoring, y, pred, w_eval, static.get("_n_classes", 2))}
        return {
            "score": regression_score(scoring, y, state, w_eval),
            "mse": weighted_mse(y, state, w_eval),
        }

    # artifact materialization (trial_map.fit_single chunked branch)
    def fit_chunk(self, X, y, w, hyper, static, chunk_idx, carry, plan):
        xb = X["xb"] if isinstance(X, dict) else X
        w = w.astype(jnp.float32)
        n_stages = int(static.get("n_estimators", 100))
        g = plan["trees_per_chunk"]
        base_key = jax.random.PRNGKey(static["_seed"])

        def one(F, i):
            t = chunk_idx * g + i
            key = jax.random.fold_in(base_key, t)
            F_new, trees = self._stage(xb, y, w, hyper, static, F, key)
            live = t < n_stages
            F_out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(live, a, b), F_new, F
            )
            return F_out, trees

        carry, trees = jax.lax.scan(one, carry, jnp.arange(g))
        return carry, trees

    def assemble_artifact(self, trees, X, hyper, static, data_y, data_w):
        params = {
            "trees": trees,
            "prior": self._prior(data_y, data_w.astype(jnp.float32), static),
            "lr": jnp.asarray(hyper["learning_rate"], jnp.float32),
        }
        if isinstance(X, dict):
            params["edges"] = X["edges"]
        return params

    hyper_defaults = {"learning_rate": 0.1, "subsample": 1.0}
    static_defaults = {
        "n_estimators": 100,
        "max_depth": 3,
        "min_samples_leaf": 1,
        "min_samples_split": 2,
        "max_features": None,
        "random_state": 0,
        "n_bins": 128,
        "loss": "default",
        "criterion": "friedman_mse",
        "init": None,
        "alpha": 0.9,
        "validation_fraction": 0.1,
        "n_iter_no_change": None,
        "tol": 1e-4,
        "min_weight_fraction_leaf": 0.0,
        "max_leaf_nodes": None,
        "min_impurity_decrease": 0.0,
        "ccp_alpha": 0.0,
    }
    _mf_default = 1.0


class GradientBoostingClassifierKernel(_GradientBoostingBase):
    name = "GradientBoostingClassifier"
    task = "classification"

    def _prior(self, y, w, static):
        c = max(int(static["_n_classes"]), 2)
        Y = jax.nn.one_hot(y, c, dtype=jnp.float32)
        wsum = jnp.maximum(jnp.sum(w), 1e-12)
        return jnp.log(jnp.maximum(jnp.sum(Y * w[:, None], 0) / wsum, 1e-12))

    def _f0(self, n, prior, static):
        c = max(int(static["_n_classes"]), 2)
        if c > 2:
            return jnp.broadcast_to(prior, (n, c))
        return jnp.stack(
            [jnp.zeros(n), jnp.broadcast_to(prior[1] - prior[0], (n,))], axis=1
        )

    def _stage(self, xb, y, w, hyper, static, F, key):
        """One boosting stage: (F, key) -> (F', per-class trees)."""
        c = max(int(static["_n_classes"]), 2)
        n = xb.shape[0]
        depth, n_bins = static["_depth"], static["_n_bins"]
        lr = jnp.asarray(hyper["learning_rate"], jnp.float32)
        subsample = jnp.asarray(hyper["subsample"], jnp.float32)
        Y = jax.nn.one_hot(y, c, dtype=jnp.float32)
        leaf_scale = (c - 1) / c if c > 2 else 1.0
        sub_key, feat_key = jax.random.split(key)
        mask = (jax.random.uniform(sub_key, (n,)) < subsample).astype(jnp.float32) * w
        P = jax.nn.softmax(F, axis=-1) if c > 2 else jax.nn.sigmoid(F)
        if c > 2:
            G = (Y - P) * mask[:, None]
            H = P * (1.0 - P) * mask[:, None]
        else:
            G = (Y[:, 1:] - P[:, 1:]) * mask[:, None]
            H = (P[:, 1:] * (1.0 - P[:, 1:])) * mask[:, None]

        def per_class(g, h, k2):
            return build_tree(
                xb,
                g[:, None],
                jnp.maximum(h, 1e-12),
                depth=depth,
                n_bins=n_bins,
                min_samples_leaf=static["_msl"],
                max_features=static["_mf"] if static["_mf"] < xb.shape[1] else None,
                key=k2,
                # log-loss gradients/hessians are bounded in [-1, 1] and
                # boosting self-corrects split noise: bf16 histogram
                # matmuls measure ~1.6x faster with unchanged CV score
                # (regression keeps HIGHEST — residual magnitudes are
                # unbounded)
                precision=jax.lax.Precision.DEFAULT,
            )

        kdim = G.shape[1]
        keys = jax.random.split(feat_key, kdim)
        trees = jax.vmap(per_class, in_axes=(1, 1, 0))(G, H, keys)

        def upd(tree):
            return predict_tree(xb, tree, depth, n_bins)[:, 0]

        delta = jax.vmap(upd)(trees).T  # [n, kdim]
        if c > 2:
            F = F + lr * leaf_scale * delta
        else:
            F = F.at[:, 1].add(lr * delta[:, 0])
        return F, trees

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        xb = X["xb"] if isinstance(X, dict) else X
        n = xb.shape[0]
        w = w.astype(jnp.float32)
        n_stages = int(static.get("n_estimators", 100))
        base_key = jax.random.PRNGKey(static["_seed"])

        def stage(F, t):
            # fold_in(t) stage keys — identical stream to the chunked paths
            return self._stage(
                xb, y, w, hyper, static, F, jax.random.fold_in(base_key, t)
            )

        _, trees = jax.lax.scan(
            stage, self._f0(n, self._prior(y, w, static), static),
            jnp.arange(n_stages),
        )
        return self.assemble_artifact(trees, X, hyper, static, y, w)

    def _raw_scores(self, params, X, static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        depth, nbq = static["_depth"], static["_n_bins"]
        xq = self._query_bins(params, X, static)
        prior = params["prior"]
        lr = params["lr"]
        leaf_scale = (c - 1) / c if c > 2 else 1.0

        def per_stage(F, stage_trees):
            def upd(tree):
                return predict_tree(xq, tree, depth, nbq)[:, 0]

            delta = jax.vmap(upd)(stage_trees).T
            if c > 2:
                return F + lr * leaf_scale * delta, None
            return F.at[:, 1].add(lr * delta[:, 0]), None

        n = xq.shape[0]
        F0 = (
            jnp.broadcast_to(prior, (n, c))
            if c > 2
            else jnp.stack(
                [jnp.zeros(n), jnp.broadcast_to(prior[1] - prior[0], (n,))], axis=1
            )
        )
        F, _ = jax.lax.scan(per_stage, F0, params["trees"])
        return F

    def predict(self, params, X, static: Dict[str, Any]):
        return jnp.argmax(self._raw_scores(params, X, static), axis=-1).astype(jnp.int32)

    def predict_margin(self, params, X, static: Dict[str, Any]):
        F = self._raw_scores(params, X, static)
        return F[:, 1] - F[:, 0]

    def predict_proba(self, params, X, static: Dict[str, Any]):
        """Softmax over raw boosting scores (sklearn GBT predict_proba)."""
        return jax.nn.softmax(self._raw_scores(params, X, static), axis=-1)


class GradientBoostingRegressorKernel(_GradientBoostingBase):
    name = "GradientBoostingRegressor"
    task = "regression"

    def _prior(self, y, w, static):
        wsum = jnp.maximum(jnp.sum(w), 1e-12)
        return jnp.sum(y.astype(jnp.float32) * w) / wsum

    def _f0(self, n, prior, static):
        return jnp.full((n,), prior)

    def _stage(self, xb, y, w, hyper, static, F, key):
        n = xb.shape[0]
        depth, n_bins = static["_depth"], static["_n_bins"]
        lr = jnp.asarray(hyper["learning_rate"], jnp.float32)
        subsample = jnp.asarray(hyper["subsample"], jnp.float32)
        sub_key, feat_key = jax.random.split(key)
        mask = (jax.random.uniform(sub_key, (n,)) < subsample).astype(jnp.float32) * w
        g = (y.astype(jnp.float32) - F) * mask
        tree = build_tree(
            xb,
            g[:, None],
            mask,
            depth=depth,
            n_bins=n_bins,
            min_samples_leaf=static["_msl"],
            max_features=static["_mf"] if static["_mf"] < xb.shape[1] else None,
            key=feat_key,
        )
        F = F + lr * predict_tree(xb, tree, depth, n_bins)[:, 0]
        return F, tree

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        xb = X["xb"] if isinstance(X, dict) else X
        n = xb.shape[0]
        w = w.astype(jnp.float32)
        n_stages = int(static.get("n_estimators", 100))
        base_key = jax.random.PRNGKey(static["_seed"])

        def stage(F, t):
            # fold_in(t) stage keys — identical stream to the chunked paths
            return self._stage(
                xb, y, w, hyper, static, F, jax.random.fold_in(base_key, t)
            )

        _, trees = jax.lax.scan(
            stage, self._f0(n, self._prior(y, w, static), static),
            jnp.arange(n_stages),
        )
        return self.assemble_artifact(trees, X, hyper, static, y, w)

    def predict(self, params, X, static: Dict[str, Any]):
        depth, nbq = static["_depth"], static["_n_bins"]
        xq = self._query_bins(params, X, static)
        lr = params["lr"]

        def per_stage(F, tree):
            return F + lr * predict_tree(xq, tree, depth, nbq)[:, 0], None

        F0 = jnp.full((xq.shape[0],), params["prior"])
        F, _ = jax.lax.scan(per_stage, F0, params["trees"])
        return F


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(RandomForestClassifierKernel())
register_kernel(RandomForestRegressorKernel())
register_kernel(GradientBoostingClassifierKernel())
register_kernel(GradientBoostingRegressorKernel())
