"""Model registry: sklearn class name -> TPU kernel.

Replaces the reference's exec/eval-based dynamic import whitelist
(``aws-prod/worker/worker.py:36-57, 436-455`` — flagged in SURVEY.md as a
security hole) with an explicit registry. The target surface is the same 15
names: 5 classifiers, 5 regressors, 5 transformers.
"""

from __future__ import annotations

from typing import Dict, List

from .base import ModelKernel

_REGISTRY: Dict[str, ModelKernel] = {}


def register_kernel(kernel: ModelKernel) -> ModelKernel:
    _REGISTRY[kernel.name] = kernel
    return kernel


def get_kernel(model_type: str) -> ModelKernel:
    _ensure_populated()
    try:
        return _REGISTRY[model_type]
    except KeyError:
        raise ValueError(
            f"Unsupported model type {model_type!r}. Supported: {sorted(_REGISTRY)}"
        ) from None


def supported_models() -> List[str]:
    _ensure_populated()
    return sorted(_REGISTRY)


_populated = False


def _ensure_populated() -> None:
    global _populated
    if _populated:
        return
    from .linear import LinearRegressionKernel, RidgeKernel
    from .logistic import LogisticRegressionKernel

    register_kernel(LogisticRegressionKernel())
    register_kernel(LinearRegressionKernel())
    register_kernel(RidgeKernel())
    _populated = True
    # Remaining families land with their modules (see models/):
    for optional in ("knn", "svm", "trees", "mlp", "transforms", "naive_bayes"):
        try:
            __import__(f"{__package__}.{optional}")
        except ImportError:
            pass
