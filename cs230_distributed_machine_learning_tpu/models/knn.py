"""K-nearest-neighbors kernels (classifier + regressor), MXU-first.

Capability target: the reference's `KNeighborsClassifier` /
`KNeighborsRegressor` trials (``aws-prod/worker/worker.py:45,51``). The
distance computation is the classic ||q||^2 + ||x||^2 - 2 q.x expansion —
one [B,d]x[d,n] matmul per query block, exactly the shape the MXU wants —
with queries processed in fixed-size blocks via ``lax.map`` so the [n,n]
distance matrix never materializes for large datasets.

"Fitting" a KNN is storing the training set: here that's the {0,1} split
mask (the full X/y arrays are shared by every split and trial), so the K+1
CV fits per trial are free. ``n_neighbors`` changes the top-k shape and is
therefore static (one compile bucket per k, as SURVEY.md §7's bucketing
prescribes); ``weights`` ("uniform" | "distance") is static control flow.

sklearn-matching details: Euclidean (minkowski p=2) metric; distance
weighting uses 1/d with exact-match (d=0) queries collapsing onto the
matched neighbors; classification ties resolve to the smallest label, which
argmax-over-counts reproduces.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .base import ModelKernel

_QUERY_BLOCK = 1024
_TRAIN_TILE = 16384
#: neighbor counts at or below this use k min-extractions in place of
#: lax.top_k (see tile_step) — the crossover where k sequential row
#: reductions beat the sort network over the tile width
_SMALL_K = 16
# above this many training rows on TPU, use the fused Pallas top-k kernel
# (streams train tiles through VMEM; the XLA path streams the same tiles
# but pays a per-tile sort-based top-k merge in HBM)
_PALLAS_MIN_N = 150_000


def _use_pallas(n: int) -> bool:
    if n < _PALLAS_MIN_N:
        return False
    import jax

    return jax.default_backend() not in ("cpu",)


class _KNNBase(ModelKernel):
    hyper_defaults: Dict[str, float] = {}
    static_defaults = {"n_neighbors": 5, "weights": "uniform", "p": 2}

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if int(static.get("p", 2)) != 2:
            raise ValueError("KNN: only p=2 (euclidean) is supported")
        if static.get("weights") not in ("uniform", "distance"):
            raise ValueError(f"KNN: unsupported weights={static.get('weights')!r}")
        k = int(static.get("n_neighbors", 5))
        return {**static, "n_neighbors": min(k, n)}

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        return {
            "X": X.astype(jnp.float32),
            "y": y,
            "w": w.astype(jnp.float32),
        }

    def _neighbors(self, params, Q, static):
        """Per query: (top-k distances^2, top-k train indices)."""
        k = int(static["n_neighbors"])
        Xt = params["X"]
        w = params["w"]
        if _use_pallas(Xt.shape[0]):
            from ..ops.pallas_knn import knn_topk

            return knn_topk(Q, Xt, w, k)
        big = jnp.float32(3.4e38)
        n, d = Xt.shape

        # train side padded to tile multiples; padded rows carry w=0 so
        # they are masked to +inf distance
        T = min(_TRAIN_TILE, max(n, 1))
        n_tp = ((n + T - 1) // T) * T
        Xtp = jnp.pad(Xt, ((0, n_tp - n), (0, 0)))
        wp = jnp.pad(w, (0, n_tp - n))
        sq_tp = jnp.sum(Xtp * Xtp, axis=1)

        nq = Q.shape[0]
        pad = (-nq) % _QUERY_BLOCK
        Qp = jnp.pad(Q, ((0, pad), (0, 0)))
        blocks = Qp.reshape(-1, _QUERY_BLOCK, d)

        def one_block(qb):
            sq_q = jnp.sum(qb * qb, axis=1, keepdims=True)

            # stream train tiles, merging into a running top-k: peak memory
            # is [block, tile + k], never [block, n] (an n x n distance/sort
            # workspace faults the device at Covertype scale). Tie-break to
            # the smallest train index (sklearn order): earlier tiles sit
            # first in the merge concat and lax.top_k prefers lower
            # positions on ties.
            def tile_step(carry, tstart):
                best_d, best_i = carry
                xt = jax.lax.dynamic_slice(Xtp, (tstart, 0), (T, d))
                st = jax.lax.dynamic_slice(sq_tp, (tstart,), (T,))
                wt = jax.lax.dynamic_slice(wp, (tstart,), (T,))
                d2 = sq_q + st[None, :] - 2.0 * (qb @ xt.T)
                d2 = jnp.where(wt[None, :] > 0, jnp.maximum(d2, 0.0), big)
                cat_d = jnp.concatenate([best_d, d2], axis=1)
                idx_tile = jnp.broadcast_to(
                    tstart + jnp.arange(T, dtype=jnp.int32)[None, :], d2.shape
                )
                cat_i = jnp.concatenate([best_i, idx_tile], axis=1)
                if k <= _SMALL_K:
                    # k min-extractions instead of lax.top_k's full sort
                    # network over the tile width — each extraction is a
                    # pair of row reductions plus one masked pass, all VPU
                    # vector ops (the 11.6k-row model-matrix KNN fit went
                    # 0.92 -> 0.13 s steady, identical CV; top_k was the
                    # whole cost). argmin takes the FIRST minimum,
                    # preserving sklearn's smaller-train-index tie order
                    # like top_k's lower-position preference did.
                    iota = jax.lax.broadcasted_iota(
                        jnp.int32, cat_d.shape, 1
                    )
                    cur = cat_d
                    ds, is_ = [], []
                    for _ in range(k):
                        j = jnp.argmin(cur, axis=1)[:, None]
                        hit = iota == j
                        ds.append(jnp.min(cur, axis=1, keepdims=True))
                        is_.append(
                            jnp.sum(jnp.where(hit, cat_i, 0), axis=1,
                                    keepdims=True)
                        )
                        cur = jnp.where(hit, big, cur)
                    return (
                        jnp.concatenate(ds, axis=1),
                        jnp.concatenate(is_, axis=1),
                    ), None
                neg, sel = jax.lax.top_k(-cat_d, k)
                return (-neg, jnp.take_along_axis(cat_i, sel, axis=1)), None

            init = (
                jnp.full((qb.shape[0], k), big),
                jnp.zeros((qb.shape[0], k), jnp.int32),
            )
            (best_d, best_i), _ = jax.lax.scan(
                tile_step, init, jnp.arange(0, n_tp, T, dtype=jnp.int32)
            )
            return best_d, best_i

        d2s, idxs = jax.lax.map(one_block, blocks)
        return (
            d2s.reshape(-1, k)[:nq],
            idxs.reshape(-1, k)[:nq],
        )

    @staticmethod
    def _vote_weights(d2, static):
        if static.get("weights") == "distance":
            d = jnp.sqrt(jnp.maximum(d2, 0.0))
            inv = 1.0 / jnp.maximum(d, 1e-12)
            # sklearn: if any neighbor matches exactly, only exact matches vote
            has_zero = jnp.any(d <= 1e-12, axis=1, keepdims=True)
            zero_w = (d <= 1e-12).astype(jnp.float32)
            return jnp.where(has_zero, zero_w, inv)
        return jnp.ones_like(d2)

    def memory_estimate_mb(self, n, d, static):
        # tiled top-k workspace: [QUERY_BLOCK, TRAIN_TILE] per split plus
        # the shared [n, d] dataset (the [block, n] full distance matrix no
        # longer exists)
        return max(1.0, 4.0 * (n * d + 3 * _QUERY_BLOCK * _TRAIN_TILE) / 1e6)

    def macs_estimate(self, n, d, static):
        """Scoring-time n x n distance sweep dominates (fit is free)."""
        return float(n) * n * max(d, 1)

    # ---- chunked-fit protocol (parallel/trial_map.py chunked path) ----
    # KNN "training" is free; the cost is the n_query x n_train distance
    # sweep at scoring time. Chunks split the QUERY rows: each dispatch
    # predicts one row range into an accumulating prediction vector, so the
    # per-dispatch device time stays bounded at any dataset size.

    def chunked_plan(self, static, n, d, n_classes, n_splits):
        # per-dispatch budget from measured effective throughput. Large k
        # pays lax.top_k's per-tile sort merge (~2.5e10 MACs/s — far below
        # the matmul-bound kernels); k <= _SMALL_K rides the min-extraction
        # path, measured ~6.6x faster (0.92 -> 0.14 s on the 11.6k model-
        # matrix fit), so its budget scales up accordingly — the stale
        # small budget would issue ~7x more dispatches than the bounded-
        # device-time target needs.
        # the raised budget applies only when the min-extraction path will
        # actually run: k <= _SMALL_K AND the Pallas top-k kernel is NOT
        # taking over (same gate the kernel uses — n >= _PALLAS_MIN_N on an
        # accelerator backend; the Pallas path's throughput the 6.6x
        # measurement does not cover, so its budget stays conservative)
        # gate on the PER-FOLD training rows the kernel will actually see
        # (~(s-1)/s of n under s-fold CV), matching _neighbors' own check
        train_rows = n if n_splits <= 1 else (n * (n_splits - 1)) // n_splits
        small_path = (
            int(static.get("n_neighbors", 5)) <= _SMALL_K
            and not _use_pallas(train_rows)
        )
        default = 1.6e12 if small_path else 2.5e11
        chunk_macs = float(os.environ.get("CS230_KNN_CHUNK_MACS", default))
        macs = float(max(n_splits, 1)) * n * n * max(d, 1)
        n_chunks = int(np.ceil(macs / chunk_macs))
        if n_chunks <= 1:
            return None
        q = int(np.ceil(n / n_chunks))
        q = max(_QUERY_BLOCK, ((q + _QUERY_BLOCK - 1) // _QUERY_BLOCK) * _QUERY_BLOCK)
        n_chunks = int(np.ceil(n / q))
        if n_chunks <= 1:  # rounding collapsed it: monolithic is cheaper
            return None
        return {"n_chunks": n_chunks, "rows_per_chunk": q}

    def _chunk_state_dtype(self):
        return jnp.int32 if self.task == "classification" else jnp.float32

    def chunk_init(self, X, y, w, hyper, static):
        return jnp.zeros((X.shape[0],), self._chunk_state_dtype())

    def chunk_step(self, X, y, w, hyper, static, chunk_idx, state, plan):
        Xa = X.astype(jnp.float32)
        q = plan["rows_per_chunk"]
        n = Xa.shape[0]
        # dynamic_slice clamps the start, so the final (ragged) chunk
        # re-predicts a few overlapping rows with identical values
        start = jnp.minimum(chunk_idx * q, max(n - q, 0))
        Q = jax.lax.dynamic_slice(Xa, (start, 0), (min(q, n), Xa.shape[1]))
        params = self.fit(Xa, y, w, hyper, static)
        preds = self.predict(params, Q, static).astype(self._chunk_state_dtype())
        return jax.lax.dynamic_update_slice(state, preds, (start,))

    def chunk_eval(self, X, y, w_eval, hyper, static, state):
        from ..ops.metrics import (
            classification_score,
            regression_score,
            weighted_mse,
        )

        scoring = static.get("_scoring")
        if self.task == "classification":
            return {"score": classification_score(
                scoring, y, state, w_eval, static.get("_n_classes", 2))}
        return {
            "score": regression_score(scoring, y, state, w_eval),
            "mse": weighted_mse(y, state, w_eval),
        }


class KNNClassifierKernel(_KNNBase):
    name = "KNeighborsClassifier"
    task = "classification"

    def predict(self, params, X, static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        d2, idx = self._neighbors(params, X.astype(jnp.float32), static)
        labels = params["y"][idx]  # [nq, k]
        votes = self._vote_weights(d2, static)
        counts = jnp.sum(jax.nn.one_hot(labels, c, dtype=jnp.float32) * votes[..., None], axis=1)
        return jnp.argmax(counts, axis=-1).astype(jnp.int32)


class KNNRegressorKernel(_KNNBase):
    name = "KNeighborsRegressor"
    task = "regression"

    def predict(self, params, X, static: Dict[str, Any]):
        d2, idx = self._neighbors(params, X.astype(jnp.float32), static)
        targets = params["y"][idx].astype(jnp.float32)
        votes = self._vote_weights(d2, static)
        return jnp.sum(targets * votes, axis=1) / jnp.maximum(jnp.sum(votes, axis=1), 1e-12)


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(KNNClassifierKernel())
register_kernel(KNNRegressorKernel())
