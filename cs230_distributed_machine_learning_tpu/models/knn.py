"""K-nearest-neighbors kernels (classifier + regressor), MXU-first.

Capability target: the reference's `KNeighborsClassifier` /
`KNeighborsRegressor` trials (``aws-prod/worker/worker.py:45,51``). The
distance computation is the classic ||q||^2 + ||x||^2 - 2 q.x expansion —
one [B,d]x[d,n] matmul per query block, exactly the shape the MXU wants —
with queries processed in fixed-size blocks via ``lax.map`` so the [n,n]
distance matrix never materializes for large datasets.

"Fitting" a KNN is storing the training set: here that's the {0,1} split
mask (the full X/y arrays are shared by every split and trial), so the K+1
CV fits per trial are free. ``n_neighbors`` changes the top-k shape and is
therefore static (one compile bucket per k, as SURVEY.md §7's bucketing
prescribes); ``weights`` ("uniform" | "distance") is static control flow.

sklearn-matching details: Euclidean (minkowski p=2) metric; distance
weighting uses 1/d with exact-match (d=0) queries collapsing onto the
matched neighbors; classification ties resolve to the smallest label, which
argmax-over-counts reproduces.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .base import ModelKernel

_QUERY_BLOCK = 1024
# above this many training rows on TPU, use the fused Pallas top-k kernel
# (streams train tiles through VMEM; the XLA path would materialize a
# [block, n] distance matrix per query block)
_PALLAS_MIN_N = 150_000


def _use_pallas(n: int) -> bool:
    if n < _PALLAS_MIN_N:
        return False
    import jax

    return jax.default_backend() not in ("cpu",)


class _KNNBase(ModelKernel):
    hyper_defaults: Dict[str, float] = {}
    static_defaults = {"n_neighbors": 5, "weights": "uniform", "p": 2}

    def resolve_static(self, static: Dict[str, Any], n: int, d: int, n_classes: int):
        if int(static.get("p", 2)) != 2:
            raise ValueError("KNN: only p=2 (euclidean) is supported")
        if static.get("weights") not in ("uniform", "distance"):
            raise ValueError(f"KNN: unsupported weights={static.get('weights')!r}")
        k = int(static.get("n_neighbors", 5))
        return {**static, "n_neighbors": min(k, n)}

    def fit(self, X, y, w, hyper: Dict[str, Any], static: Dict[str, Any]):
        return {
            "X": X.astype(jnp.float32),
            "y": y,
            "w": w.astype(jnp.float32),
        }

    def _neighbors(self, params, Q, static):
        """Per query: (top-k distances^2, top-k train indices)."""
        k = int(static["n_neighbors"])
        Xt = params["X"]
        w = params["w"]
        if _use_pallas(Xt.shape[0]):
            from ..ops.pallas_knn import knn_topk

            return knn_topk(Q, Xt, w, k)
        sq_t = jnp.sum(Xt * Xt, axis=1)  # [n]
        big = jnp.float32(3.4e38)

        nq = Q.shape[0]
        pad = (-nq) % _QUERY_BLOCK
        Qp = jnp.pad(Q, ((0, pad), (0, 0)))
        blocks = Qp.reshape(-1, _QUERY_BLOCK, Q.shape[1])

        def one_block(qb):
            d2 = (
                jnp.sum(qb * qb, axis=1, keepdims=True)
                + sq_t[None, :]
                - 2.0 * (qb @ Xt.T)
            )
            d2 = jnp.where(w[None, :] > 0, jnp.maximum(d2, 0.0), big)
            neg, idx = jax.lax.top_k(-d2, k)
            return -neg, idx

        d2s, idxs = jax.lax.map(one_block, blocks)
        return (
            d2s.reshape(-1, k)[:nq],
            idxs.reshape(-1, k)[:nq],
        )

    @staticmethod
    def _vote_weights(d2, static):
        if static.get("weights") == "distance":
            d = jnp.sqrt(jnp.maximum(d2, 0.0))
            inv = 1.0 / jnp.maximum(d, 1e-12)
            # sklearn: if any neighbor matches exactly, only exact matches vote
            has_zero = jnp.any(d <= 1e-12, axis=1, keepdims=True)
            zero_w = (d <= 1e-12).astype(jnp.float32)
            return jnp.where(has_zero, zero_w, inv)
        return jnp.ones_like(d2)

    def memory_estimate_mb(self, n, d, static):
        return max(1.0, 4.0 * (n * d + _QUERY_BLOCK * n) / 1e6)


class KNNClassifierKernel(_KNNBase):
    name = "KNeighborsClassifier"
    task = "classification"

    def predict(self, params, X, static: Dict[str, Any]):
        c = max(int(static["_n_classes"]), 2)
        d2, idx = self._neighbors(params, X.astype(jnp.float32), static)
        labels = params["y"][idx]  # [nq, k]
        votes = self._vote_weights(d2, static)
        counts = jnp.sum(jax.nn.one_hot(labels, c, dtype=jnp.float32) * votes[..., None], axis=1)
        return jnp.argmax(counts, axis=-1).astype(jnp.int32)


class KNNRegressorKernel(_KNNBase):
    name = "KNeighborsRegressor"
    task = "regression"

    def predict(self, params, X, static: Dict[str, Any]):
        d2, idx = self._neighbors(params, X.astype(jnp.float32), static)
        targets = params["y"][idx].astype(jnp.float32)
        votes = self._vote_weights(d2, static)
        return jnp.sum(targets * votes, axis=1) / jnp.maximum(jnp.sum(votes, axis=1), 1e-12)


from .registry import register_kernel  # noqa: E402  (self-registration on import)

register_kernel(KNNClassifierKernel())
register_kernel(KNNRegressorKernel())
