// Native data-plane CSV loader.
//
// The reference's data plane is pandas re-reading CSVs from a shared volume
// for every subtask (reference worker.py:424-425, dataset_util.py:119-136).
// This framework parses once into a columnar cache; this library makes that
// one parse native: mmap the file, scan dimensions, then parse all numeric
// cells to float32 with a thread pool over row chunks. Non-numeric columns
// are detected and reported so the Python side can fall back to pandas
// label-encoding for those tables (small demo datasets); large benchmark
// tables (covertype, MNIST, synthetics) are fully numeric and take the
// native path end-to-end.
//
// C API (ctypes, see native/__init__.py):
//   csv_dims(path, *n_rows, *n_cols) -> 0 ok / <0 errno-style
//   csv_parse_f32(path, out, n_rows, n_cols, col_numeric_ok) -> rows parsed
//
// Contract: header row required (skipped); delimiter ','; rows beyond
// n_rows or cells beyond n_cols are ignored; empty trailing lines skipped;
// a cell that fails float parse writes NaN and clears its column's
// numeric_ok flag.

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
  Mapped m;
  m.fd = ::open(path, O_RDONLY);
  if (m.fd < 0) return m;
  struct stat st;
  if (::fstat(m.fd, &st) != 0 || st.st_size == 0) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  void* p = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
  if (p == MAP_FAILED) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  m.data = static_cast<const char*>(p);
  m.size = static_cast<size_t>(st.st_size);
  return m;
}

void unmap(Mapped& m) {
  if (m.data) ::munmap(const_cast<char*>(m.data), m.size);
  if (m.fd >= 0) ::close(m.fd);
  m.data = nullptr;
  m.fd = -1;
}

// End of the header line (first '\n'), or size if single-line file.
size_t header_end(const Mapped& m) {
  const char* nl = static_cast<const char*>(memchr(m.data, '\n', m.size));
  return nl ? static_cast<size_t>(nl - m.data) + 1 : m.size;
}

size_t count_cols(const Mapped& m) {
  size_t end = header_end(m);
  size_t cols = 1;
  for (size_t i = 0; i < end; i++) {
    if (m.data[i] == ',') cols++;
  }
  return cols;
}

// Parse one data line into row-major out[row * n_cols .. ]. Flags columns
// whose cells fail float parse. file_end bounds the mapping: the very last
// cell of the file may end flush against it with no delimiter, and strtof
// on the raw pointer would read past the mapping (SIGSEGV when the file
// size is an exact page multiple) — that one case is parsed from a bounded
// local copy instead.
void parse_line(const char* p, const char* line_end, const char* file_end,
                float* out_row, int64_t n_cols, uint8_t* col_numeric_ok) {
  int64_t col = 0;
  while (col < n_cols && p <= line_end) {
    const char* cell_end =
        static_cast<const char*>(memchr(p, ',', line_end - p));
    if (!cell_end) cell_end = line_end;
    // strtof stops at the first invalid char, so parsing in place against
    // the ','/'\n' boundary is safe everywhere except flush at file_end.
    const char* s = p;
    while (s < cell_end && (*s == ' ' || *s == '\t')) s++;
    const char* e = cell_end;
    while (e > s && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r')) e--;
    if (s == e) {
      out_row[col] = NAN;  // empty cell: missing value, still "numeric"
    } else {
      char* parse_end = nullptr;
      float v;
      if (e == file_end) {
        char buf[64];
        size_t len = static_cast<size_t>(e - s);
        if (len >= sizeof(buf)) len = sizeof(buf) - 1;
        memcpy(buf, s, len);
        buf[len] = '\0';
        v = strtof(buf, &parse_end);
        parse_end = const_cast<char*>(s) + (parse_end - buf);
      } else {
        v = strtof(s, &parse_end);
      }
      if (parse_end == e) {
        out_row[col] = v;
      } else {
        out_row[col] = NAN;
        col_numeric_ok[col] = 0;
      }
    }
    col++;
    p = cell_end + 1;
  }
  // Ragged short row: fewer cells than the header promises. This is not a
  // missing value — it signals a header the naive comma count mis-parsed
  // (e.g. quoted names containing commas), so poison the phantom columns
  // to force the caller's pandas fallback.
  while (col < n_cols) {
    out_row[col] = NAN;
    col_numeric_ok[col] = 0;
    col++;
  }
}

}  // namespace

extern "C" {

// Fast dimension scan: n_rows = data lines (header excluded, blank lines
// ignored), n_cols from the header. Replaces the Python
// sum(1 for _ in open(path)) in collect_csv_metadata.
int csv_dims(const char* path, int64_t* n_rows, int64_t* n_cols) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  *n_cols = static_cast<int64_t>(count_cols(m));
  size_t start = header_end(m);
  // Parallel newline count over chunks.
  size_t body = m.size - start;
  unsigned n_threads = std::thread::hardware_concurrency();
  if (n_threads == 0) n_threads = 1;
  if (body < (1u << 20)) n_threads = 1;
  std::vector<int64_t> counts(n_threads, 0);
  std::vector<std::thread> workers;
  size_t chunk = body / n_threads + 1;
  for (unsigned t = 0; t < n_threads; t++) {
    size_t lo = start + t * chunk;
    size_t hi = lo + chunk < m.size ? lo + chunk : m.size;
    if (lo >= hi) break;
    workers.emplace_back([&, t, lo, hi]() {
      const char* p = m.data + lo;
      const char* end = m.data + hi;
      int64_t c = 0;
      while (p < end) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        if (!nl) break;
        c++;
        p = nl + 1;
      }
      counts[t] = c;
    });
  }
  for (auto& w : workers) w.join();
  int64_t rows = 0;
  for (int64_t c : counts) rows += c;
  // A final line without trailing newline is still a row.
  if (m.size > start && m.data[m.size - 1] != '\n') rows++;
  *n_rows = rows;
  unmap(m);
  return 0;
}

// Parse the file body into out (row-major float32, n_rows x n_cols).
// col_numeric_ok must be n_cols bytes, preset to 1 by the caller; cleared
// for any column containing a non-float cell. Returns rows parsed (>=0) or
// <0 on IO error.
int64_t csv_parse_f32(const char* path, float* out, int64_t n_rows,
                      int64_t n_cols, uint8_t* col_numeric_ok) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  size_t start = header_end(m);

  // Index line starts first (cheap scan) so parsing can be parallel with
  // exact row -> output-slot mapping.
  std::vector<const char*> line_starts;
  line_starts.reserve(static_cast<size_t>(n_rows));
  {
    const char* p = m.data + start;
    const char* end = m.data + m.size;
    while (p < end && static_cast<int64_t>(line_starts.size()) < n_rows) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
      const char* line_end = nl ? nl : end;
      if (line_end > p && !(line_end == p + 1 && *p == '\r')) {
        line_starts.push_back(p);
      }
      if (!nl) break;
      p = nl + 1;
    }
  }
  int64_t rows = static_cast<int64_t>(line_starts.size());

  unsigned n_threads = std::thread::hardware_concurrency();
  if (n_threads == 0) n_threads = 1;
  if (rows < 4096) n_threads = 1;
  // Per-thread column flags merged at the end (avoids false sharing/races).
  std::vector<std::vector<uint8_t>> flags(
      n_threads, std::vector<uint8_t>(static_cast<size_t>(n_cols), 1));
  std::vector<std::thread> workers;
  int64_t chunk = rows / n_threads + 1;
  const char* file_end = m.data + m.size;
  for (unsigned t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < rows ? lo + chunk : rows;
    if (lo >= hi) break;
    workers.emplace_back([&, t, lo, hi]() {
      for (int64_t r = lo; r < hi; r++) {
        const char* p = line_starts[static_cast<size_t>(r)];
        const char* scan_end =
            (r + 1 < rows) ? line_starts[static_cast<size_t>(r + 1)] : file_end;
        const char* nl =
            static_cast<const char*>(memchr(p, '\n', scan_end - p));
        const char* line_end = nl ? nl : scan_end;
        parse_line(p, line_end, file_end, out + r * n_cols, n_cols,
                   flags[t].data());
      }
    });
  }
  for (auto& w : workers) w.join();
  for (unsigned t = 0; t < n_threads; t++) {
    for (int64_t c = 0; c < n_cols; c++) {
      if (!flags[t][static_cast<size_t>(c)]) col_numeric_ok[c] = 0;
    }
  }
  unmap(m);
  return rows;
}

}  // extern "C"
