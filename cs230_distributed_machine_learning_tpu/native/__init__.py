"""Native (C++) data-plane components, ctypes-bound.

The compute path of this framework is JAX/XLA; the runtime around it uses
native code where the hot path is host-bound. First component: the CSV
loader (csv_loader.cpp) — mmap + multithreaded parse replacing pandas for
fully-numeric tables (covertype, MNIST, synthetics) and the Python
line-count in metadata collection (reference dataset_util.py:119-136).

The shared library is compiled on first use with g++ into the storage root
(keyed by source hash, so upgrades rebuild) and loaded with ctypes — no
pybind11 dependency. Every caller must handle ``get_lib() is None`` and
fall back to the pure-Python path: machines without a toolchain lose speed,
not capability.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "csv_loader.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build_dir() -> str:
    from ..utils.config import get_config

    return os.path.join(get_config().storage.root, "native")


def _compile(src: str, out: str) -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        src, "-o", out,
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0 and os.path.exists(out)
    except (OSError, subprocess.TimeoutExpired):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, compiling it on first call; None if the
    source is missing, g++ is unavailable, or compilation fails."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            with open(_SRC, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
            so_path = os.path.join(_build_dir(), f"csv_loader_{tag}.so")
            if not os.path.exists(so_path):
                os.makedirs(os.path.dirname(so_path), exist_ok=True)
                tmp = so_path + f".build{os.getpid()}"
                if not _compile(_SRC, tmp):
                    _lib_failed = True
                    return None
                os.replace(tmp, so_path)  # atomic vs concurrent builders
            lib = ctypes.CDLL(so_path)
            lib.csv_dims.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.csv_dims.restype = ctypes.c_int
            lib.csv_parse_f32.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.csv_parse_f32.restype = ctypes.c_int64
            _lib = lib
        except Exception:  # noqa: BLE001 — any failure degrades to Python
            _lib_failed = True
        return _lib


def csv_dims(path: str) -> Optional[Tuple[int, int]]:
    """(n_rows, n_cols) of a headered CSV via the native scanner, or None."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    if lib.csv_dims(path.encode(), ctypes.byref(rows), ctypes.byref(cols)) != 0:
        return None
    return int(rows.value), int(cols.value)


def csv_parse_f32(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a headered CSV to (matrix float32 [rows, cols], numeric_ok bool
    per column). Returns None when the native path is unavailable or the
    file can't be read; the caller decides what to do with non-numeric
    columns (this framework: fall back to pandas label-encoding)."""
    lib = get_lib()
    if lib is None:
        return None
    dims = csv_dims(path)
    if dims is None or dims[0] <= 0 or dims[1] <= 0:
        return None
    n_rows, n_cols = dims
    out = np.empty((n_rows, n_cols), dtype=np.float32)
    ok = np.ones(n_cols, dtype=np.uint8)
    parsed = lib.csv_parse_f32(
        path.encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_rows,
        n_cols,
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if parsed < 0:
        return None
    return out[:parsed], ok.astype(bool)
