#!/usr/bin/env bash
# CI gate, runnable locally or from .github/workflows/ci.yml:
#   ./ci.sh [fast|kernels|chaos|search|perf|loadtest|multichip|streaming|obs|trace|rebalance|curves]
#   (default: fast)
#
#   fast mode:
#   1. compileall lint gate — every .py in the package, tests, and
#      benchmarks must byte-compile (catches syntax/indent rot with no
#      deps beyond the stdlib);
#   2. tier-1 fast suite — the ROADMAP.md verify command: pytest on the
#      virtual 8-device CPU mesh, slow (subprocess/chaos/minutes-long)
#      suites excluded. This includes the PR-8 data-plane suites
#      (tests/test_stage_cache.py: single-flight staging, refcount/LRU
#      eviction, fingerprint collision safety, CS230_STAGE_CACHE=0
#      parity; tests/test_prewarm.py: hint derivation, yield-to-work,
#      never-warm-twice, /subscribe handshake);
#   3. sharded control-plane smoke — 2 coordinator-shard subprocesses
#      behind a stateless front end (runtime/frontend.py), reduced client
#      count, asserting completion + routing (no absolute-latency gate),
#      so the front/core split topology is exercised on every run.
#
#   loadtest mode (nightly/dispatch in ci.yml): the FULL 4-shard
#   control-plane load test (benchmarks/loadtest.py, ROADMAP item 2
#   harness) with the functional smoke gate; the fresh
#   loadtest_4shard.json is uploaded as a workflow artifact.
#
#   kernels mode: the interpret-mode kernel-parity suites ONLY — every
#   Pallas kernel (packed/masked logreg gradients, the fused packed
#   Nesterov step incl. its aliasing + convergence-mask-edge contracts,
#   level histogram, MLP epoch, KNN top-k) against its XLA reference on
#   CPU, plus the valve plumbing (CS230_MASKED_GRAD / CS230_FUSED_STEP /
#   CS230_HIST_KERNEL) end to end. A few minutes; the job that makes a
#   TPU-kernel regression fail without a TPU. Recipe + parity
#   contracts: docs/KERNELS.md.
#
#   search mode: the adaptive-search suites standalone (docs/SEARCH.md) —
#   the ASHA/Hyperband controller unit suite plus the e2e cluster runs
#   (prune mid-flight, degenerate-eta winner parity, the rung
#   journal-replay drill), then the committed adaptive-search benchmark
#   (ASHA vs exhaustive RandomizedSearch on the covertype config; gate:
#   score parity ±1e-3 AND <= 0.5x device-seconds) which refreshes
#   benchmarks/ADAPTIVE_SEARCH.json into bench-artifacts/.
#
#   perf mode (manually-triggered + nightly in ci.yml, like chaos): the
#   valve A/B regression harness (benchmarks/perf_observatory.py) in
#   quick mode with the noise-aware gate against the committed
#   benchmarks/PERF_OBSERVATORY.json baselines — a perf valve silently
#   regressing (legacy fallback, lost cache keying) fails the job —
#   followed by an injected-regression drill (PERF_OBS_INJECT) proving
#   the gate itself still trips. Fresh measurements always land in
#   bench-artifacts/PERF_OBSERVATORY.json for upload.
#
#   multichip mode: the elastic-trial-fabric gate (docs/ARCHITECTURE.md
#   "Elastic trial fabric"). The mesh cache-parity + resharding suites
#   plus the scaling harness at 1/2 forced host devices (quick reps, no
#   >1.0x gate — the smoke proves the harness end to end; the committed
#   benchmarks/MULTICHIP_BENCH_r01.json proves the scaling). The nightly
#   ci.yml job additionally runs the FULL 1/2/4/8 curve and uploads the
#   fresh MULTICHIP_BENCH JSON for trend-watching.
#
#   streaming mode (every push in ci.yml, fast): the out-of-core
#   row-block streaming suites (tests/test_streaming.py — block-plan
#   parity, streamed-vs-single-shot score parity incl. the bitwise tree
#   pin, prefetch pinning, the CS230_STAGE_STRICT OOM repro — plus
#   tests/test_stage_cache.py, whose acquire/release + overflow-signal
#   contracts the streamer rides). With STREAMING_FULL=1
#   (nightly/dispatch) it additionally runs the full-geometry
#   benchmarks/streaming_micro.py (10x-budget OOM repro + double-buffer
#   overlap profile) and uploads the fresh STREAMING_MICRO.json.
#
#   obs mode (every push in ci.yml, fast): the fleet-health-plane gate
#   (docs/OBSERVABILITY.md "Fleet health plane") — the alert-engine /
#   capacity-signal unit suites (tests/test_fleet_health.py: burn-rate
#   windows, counter-reset clamping, hysteresis/drain gating, the pinned
#   stage_cache_overflow fire), the front-end aggregation suites
#   (tests/test_frontend_aggregation.py: merged Prometheus exposition,
#   /events cursor paging, /alerts union, /autoscale sums against fake
#   shards), and the flight-recorder metric/event catalog parity gates —
#   then the live overload→fire→drain→resolve drill
#   (benchmarks/fleet_health.py) on a real 2-shard fleet through the
#   front end, refreshing FLEET_HEALTH.json into bench-artifacts/ (the
#   committed acceptance artifact is benchmarks/FLEET_HEALTH.json).
#
#   trace mode (every push in ci.yml, fast): the critical-path /
#   trace-export gate (docs/OBSERVABILITY.md "Critical path & trace
#   export") — the engine unit suites (tests/test_critpath.py: exact
#   segment tiling, untraced-gap honesty, reclaim-wait + speculative-win
#   attribution, Perfetto/OTLP document shapes, the span-drop counter)
#   and the two-process stitching suite (tests/test_trace_propagation.py:
#   frontend.proxy roots the trace, X-Parent-Span nesting) — then the
#   live attribution drill (benchmarks/critical_path.py: baseline vs
#   injected-aggregate-slowdown through a real front end; gates segments
#   ≈ store wall and ≥80 % of the delta attributed), refreshing
#   CRITICAL_PATH.json into bench-artifacts/ and re-validating the
#   Perfetto export the drill wrote as loadable Chrome trace JSON.
#
#   curves mode (every push in ci.yml, fast): the trial-telemetry-plane
#   gate (docs/OBSERVABILITY.md "Trial telemetry plane") — the curve
#   capture/store/watchdog suites (tests/test_telemetry_curves.py: trace-tail ==
#   reported-score parity across fused+legacy scan bodies, stride
#   downsampling at non-multiple max_iter, the CS230_CURVES=0 strict
#   no-op pin, the live-socket watchdog e2e, curve-op journal truncation
#   fuzz, the SSE curve round-trip through a front end) plus the search
#   e2e suite whose diverged-terminal arithmetic curves ride. With
#   CURVES_FULL=1 (nightly/dispatch) it additionally runs
#   benchmarks/curve_micro.py (capture-overhead <= 3% gate, the
#   diverging-lr <30%-budget watchdog drill, survivor parity) and
#   uploads the fresh CURVE_MICRO.json (the committed acceptance
#   artifact is benchmarks/CURVE_MICRO.json).
#
#   rebalance mode (every push in ci.yml, fast): the cross-shard
#   rebalancing gate (docs/ROBUSTNESS.md "Shard rebalancing") — the
#   fencing/tombstone/forwarding unit suite (tests/test_rebalance.py:
#   migrate_out/migrate_in/steal journal round-trips + crash-point
#   truncation fuzz, steal-grant fencing and lease reclaim, the 409
#   forwarding stamp and the front end's bounded-TTL redirect cache,
#   live HTTP migration between two coordinators). With
#   REBALANCE_FULL=1 (nightly/dispatch) it additionally runs the full
#   skewed-hash load test (benchmarks/loadtest_skew.py --check: 80/20
#   session skew must recover >= 0.8x the even-hash jobs/s with the
#   rebalancer demonstrably acting) and uploads the fresh
#   LOADTEST_SKEW.json (the committed acceptance artifact is
#   benchmarks/LOADTEST_SKEW.json).
#
#   chaos mode (manually-triggered + nightly in ci.yml): the slow-marked
#   chaos/durability suites — fleet kill-mid-job, hung-worker lease
#   reclaim, the coordinator-SIGKILL drill (server subprocess killed
#   mid-job + restarted against the same journal dir; agents reconnect
#   and flush buffers — docs/ROBUSTNESS.md "Coordinator recovery"),
#   SPMD host loss, supervisor restart policy — which the fast gate
#   never runs. The drill writes its journal dir + process logs under
#   $CI_ARTIFACTS_DIR/coordinator_kill, so a red run uploads the
#   coordinator's jobs.jsonl and flight-recorder events.jsonl.
#
# On a RED suite the trace/metric/decision record of the run is preserved
# under $CI_ARTIFACTS_DIR (default ci-artifacts/) so failures are
# diagnosable from the span journal, the flight-recorder event journal,
# and a Prometheus snapshot instead of rerun archaeology; ci.yml uploads
# the directory as a workflow artifact.
# Wall time of the fast suite on the dev box is recorded in
# docs/STATUS.md; keep the two in sync when it moves.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-fast}"
ART_DIR="${CI_ARTIFACTS_DIR:-ci-artifacts}"

echo "== lint gate: python -m compileall =="
python -m compileall -q cs230_distributed_machine_learning_tpu tests benchmarks

# CS230_JOURNAL_DIR: every span AND flight-recorder event of the whole
# run lands in ONE journal dir (tests re-root storage per test, which
# would scatter-then-delete them);
# CS230_METRICS_SNAPSHOT: conftest dumps the suite process's registry in
# Prometheus text format at session end when the run failed;
# CS230_EVENTS_SNAPSHOT: conftest dumps the suite process's in-memory
# flight-recorder ring (the scheduling decisions of the failed run) as
# JSONL next to it.
mkdir -p "$ART_DIR"
rc=0
if [ "$MODE" = "kernels" ]; then
  echo "== interpret-mode kernel-parity suite (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_pallas_logreg.py tests/test_pallas_hist.py \
    tests/test_pallas_mlp.py tests/test_pallas_knn.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
elif [ "$MODE" = "search" ]; then
  echo "== adaptive-search suite (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_search_asha.py tests/test_search_e2e.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  echo "== adaptive-search benchmark (device-seconds gate) =="
  mkdir -p bench-artifacts
  if JAX_PLATFORMS=cpu python benchmarks/adaptive_search.py \
      > bench-artifacts/adaptive_search.log 2>&1; then
    cp benchmarks/ADAPTIVE_SEARCH.json bench-artifacts/ || true
    tail -n 1 bench-artifacts/adaptive_search.log
  else
    echo "adaptive_search FAILED (see bench-artifacts/adaptive_search.log)"
    rc=1
  fi
elif [ "$MODE" = "perf" ]; then
  echo "== perf observatory: valve A/B + noise-aware gate (quick) =="
  mkdir -p bench-artifacts
  # measure fresh (quick: fewer reps, identical shapes) and gate against
  # the committed baseline; the measurement document is uploaded either way
  if ! JAX_PLATFORMS=cpu python benchmarks/perf_observatory.py \
      --quick --check \
      --out bench-artifacts/PERF_OBSERVATORY.json \
      --baseline benchmarks/PERF_OBSERVATORY.json \
      2>&1 | tee bench-artifacts/perf_observatory.log; then
    echo "perf gate RED (see bench-artifacts/perf_observatory.log)"
    rc=1
  fi
  # all.on (not all): scaling only the fast-path states also shifts the
  # on/off delta, so the drill trips the comparator's cross-host delta
  # mode too — a uniform all= slowdown is, by design, invisible there
  echo "== injected-regression drill: the gate must trip on a synthetic 10x =="
  if PERF_OBS_INJECT="all.on=10.0" JAX_PLATFORMS=cpu \
      python benchmarks/perf_observatory.py \
      --compare-only bench-artifacts/PERF_OBSERVATORY.json \
      --baseline benchmarks/PERF_OBSERVATORY.json \
      > bench-artifacts/perf_inject_drill.log 2>&1; then
    echo "DRILL FAILED: injected regression was NOT caught"
    rc=1
  else
    echo "drill ok: injected regression tripped the gate"
  fi
elif [ "$MODE" = "chaos" ]; then
  echo "== chaos/durability suite (JAX_PLATFORMS=cpu, -m slow) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_chaos.py tests/test_chaos_spmd.py tests/test_cluster.py \
    tests/test_durability.py tests/test_fault_tolerance.py \
    -q -m slow \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  # concurrent-jobs staging benchmark: asserts exactly one upload per
  # (dataset, device) under 8 parallel jobs and refreshes the committed
  # JSON; kept OUTSIDE $ART_DIR so green runs still publish it (ci.yml
  # uploads bench-artifacts/ unconditionally on the chaos job)
  echo "== staging-concurrency benchmark (O(1) uploads contract) =="
  mkdir -p bench-artifacts
  if JAX_PLATFORMS=cpu python benchmarks/staging_concurrency.py \
      > bench-artifacts/staging_concurrency.log 2>&1; then
    cp benchmarks/STAGING_CONCURRENCY.json bench-artifacts/ || true
  else
    echo "staging_concurrency FAILED (see bench-artifacts/staging_concurrency.log)"
    rc=1
  fi
elif [ "$MODE" = "multichip" ]; then
  echo "== elastic trial fabric: mesh cache parity + resharding suites =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_stage_cache.py tests/test_resharding.py \
    tests/test_distributed_mesh.py tests/test_2d_mesh.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  echo "== multichip scaling smoke (forced 1/2 host devices, quick) =="
  mkdir -p bench-artifacts
  if JAX_PLATFORMS=cpu python benchmarks/multichip_bench.py \
      --devices 1,2 --quick --no-check \
      --out bench-artifacts/MULTICHIP_BENCH_smoke.json \
      > bench-artifacts/multichip_smoke.log 2>&1; then
    tail -n 2 bench-artifacts/multichip_smoke.log
  else
    echo "multichip smoke FAILED (see bench-artifacts/multichip_smoke.log)"
    tail -n 20 bench-artifacts/multichip_smoke.log
    rc=1
  fi
  if [ "${MULTICHIP_FULL:-0}" = "1" ]; then
    echo "== FULL multichip scaling curve (1/2/4/8, nightly) =="
    if JAX_PLATFORMS=cpu python benchmarks/multichip_bench.py \
        --out bench-artifacts/MULTICHIP_BENCH_nightly.json \
        > bench-artifacts/multichip_full.log 2>&1; then
      tail -n 5 bench-artifacts/multichip_full.log
    else
      echo "multichip full curve FAILED (see bench-artifacts/multichip_full.log)"
      tail -n 20 bench-artifacts/multichip_full.log
      rc=1
    fi
  fi
elif [ "$MODE" = "streaming" ]; then
  echo "== out-of-core streaming suite (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_streaming.py tests/test_stage_cache.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  if [ "${STREAMING_FULL:-0}" = "1" ]; then
    # nightly/dispatch: the full-geometry OOM repro (10x budget, both
    # streamed families) + double-buffer overlap profile; the fresh
    # JSON is uploaded for trend-watching (the committed acceptance
    # artifact is benchmarks/STREAMING_MICRO.json)
    echo "== FULL streaming micro-benchmark (OOM repro + overlap) =="
    mkdir -p bench-artifacts
    if JAX_PLATFORMS=cpu python benchmarks/streaming_micro.py \
        > bench-artifacts/streaming_micro.log 2>&1; then
      cp benchmarks/STREAMING_MICRO.json bench-artifacts/ || true
      tail -n 3 bench-artifacts/streaming_micro.log
    else
      echo "streaming_micro FAILED (see bench-artifacts/streaming_micro.log)"
      tail -n 20 bench-artifacts/streaming_micro.log
      rc=1
    fi
  fi
elif [ "$MODE" = "obs" ]; then
  echo "== fleet health plane suites (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_fleet_health.py tests/test_frontend_aggregation.py \
    tests/test_flight_recorder.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  # live 2-shard overload→fire→drain→resolve drill through the front
  # end; measures fresh and gates on the 8 functional assertions (alert
  # fired, desired>live, journaled fire+resolve, shard attribution, …).
  # Fresh JSON goes to bench-artifacts/ for trend-watching; the shard
  # subprocess logs land under $ART_DIR so a red drill uploads them.
  echo "== fleet health drill (2 shards, overload→fire→drain→resolve) =="
  mkdir -p bench-artifacts
  if FLEET_HEALTH_OUT=bench-artifacts/FLEET_HEALTH.json \
      FLEET_HEALTH_LOG_DIR="$ART_DIR/fleet-health-logs" \
      JAX_PLATFORMS=cpu python benchmarks/fleet_health.py \
      > bench-artifacts/fleet_health.log 2>&1; then
    tail -n 3 bench-artifacts/fleet_health.log
  else
    echo "fleet_health drill FAILED (see bench-artifacts/fleet_health.log)"
    tail -n 20 bench-artifacts/fleet_health.log
    rc=1
  fi
elif [ "$MODE" = "trace" ]; then
  echo "== critical-path / trace-export suites (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_critpath.py tests/test_trace_propagation.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  # live attribution drill: baseline vs injected aggregate slowdown
  # through a real front end; the fresh JSON is uploaded for
  # trend-watching (the committed acceptance artifact is
  # benchmarks/CRITICAL_PATH.json)
  echo "== critical-path attribution drill (inject -> diff -> attribute) =="
  mkdir -p bench-artifacts
  if CRITICAL_PATH_OUT=bench-artifacts/CRITICAL_PATH.json \
      CS230_JOURNAL_DIR="$ART_DIR/journal" \
      JAX_PLATFORMS=cpu python benchmarks/critical_path.py \
      > bench-artifacts/critical_path.log 2>&1; then
    tail -n 2 bench-artifacts/critical_path.log
  else
    echo "critical_path drill FAILED (see bench-artifacts/critical_path.log)"
    tail -n 20 bench-artifacts/critical_path.log
    rc=1
  fi
  # the drill exports the slowed job's trace as Perfetto Chrome JSON;
  # re-load it here as an independent validity gate (json.load + the
  # Chrome-trace keys ui.perfetto.dev requires)
  echo "== Perfetto export validity gate =="
  if ! python - <<'PYEOF'
import json, sys

doc = json.load(open("bench-artifacts/CRITICAL_PATH.json"))
path = (doc.get("export") or {}).get("perfetto_path")
if not path:
    sys.exit("no perfetto_path recorded in CRITICAL_PATH.json")
trace = json.load(open(path))
events = trace.get("traceEvents")
assert isinstance(events, list) and events, "traceEvents missing/empty"
for e in events:
    assert "ph" in e and "pid" in e and "name" in e, f"malformed event {e}"
    if e["ph"] == "X":
        assert "ts" in e and "dur" in e, f"complete event missing ts/dur {e}"
print(f"perfetto export ok: {len(events)} events in {path}")
PYEOF
  then
    echo "Perfetto validity gate FAILED"
    rc=1
  fi
elif [ "$MODE" = "curves" ]; then
  echo "== trial telemetry plane suites (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_telemetry_curves.py tests/test_search_e2e.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  if [ "${CURVES_FULL:-0}" = "1" ]; then
    # nightly/dispatch: the full micro-benchmark — capture overhead
    # <= 3% (interleaved on/off pairs), the diverging-lr watchdog drill
    # (< 30% of max_resource consumed), survivor parity under
    # CS230_CURVES=0; the fresh JSON is uploaded for trend-watching
    # (the committed acceptance artifact is benchmarks/CURVE_MICRO.json)
    echo "== FULL curve micro-benchmark (overhead + watchdog gates) =="
    mkdir -p bench-artifacts
    if JAX_PLATFORMS=cpu python benchmarks/curve_micro.py \
        > bench-artifacts/curve_micro.log 2>&1; then
      cp benchmarks/CURVE_MICRO.json bench-artifacts/ || true
      tail -n 3 bench-artifacts/curve_micro.log
    else
      echo "curve_micro FAILED (see bench-artifacts/curve_micro.log)"
      tail -n 20 bench-artifacts/curve_micro.log
      rc=1
    fi
  fi
elif [ "$MODE" = "rebalance" ]; then
  echo "== cross-shard rebalancing suite (JAX_PLATFORMS=cpu) =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_rebalance.py \
    -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  if [ "${REBALANCE_FULL:-0}" = "1" ]; then
    # nightly/dispatch: the full skewed-hash load test — even baseline,
    # skew with rebalancing off, skew with rebalancing on — gated on
    # recovery >= 0.8 and the rebalancer actually acting; the fresh
    # JSON is uploaded for trend-watching (the committed acceptance
    # artifact is benchmarks/LOADTEST_SKEW.json)
    echo "== FULL skewed-hash rebalance load test (recovery gate) =="
    mkdir -p bench-artifacts
    if SKEW_OUT=bench-artifacts/LOADTEST_SKEW.json \
        JAX_PLATFORMS=cpu python benchmarks/loadtest_skew.py --check \
        > bench-artifacts/loadtest_skew.log 2>&1; then
      tail -n 2 bench-artifacts/loadtest_skew.log
    else
      echo "loadtest_skew FAILED (see bench-artifacts/loadtest_skew.log)"
      tail -n 20 bench-artifacts/loadtest_skew.log
      rc=1
    fi
  fi
elif [ "$MODE" = "loadtest" ]; then
  # full sharded control-plane load test (nightly/dispatch in ci.yml):
  # 4 shard subprocesses behind 2 front ends, the ROADMAP item 2
  # acceptance harness. Measures only — the committed acceptance artifact
  # (benchmarks/loadtest_4shard.json) is produced on the dev box; this
  # job uploads the fresh run for trend-watching, with the functional
  # smoke assertions (completion + routing) as the only gate.
  echo "== 4-shard control-plane load test (no latency gate) =="
  mkdir -p bench-artifacts
  if LOADTEST_SHARDS=4 LOADTEST_FRONTENDS=2 \
      LOADTEST_OUT=bench-artifacts/loadtest_4shard.json \
      JAX_PLATFORMS=cpu python benchmarks/loadtest.py --smoke \
      > bench-artifacts/loadtest_4shard.log 2>&1; then
    tail -n 2 bench-artifacts/loadtest_4shard.log
  else
    echo "loadtest FAILED (see bench-artifacts/loadtest_4shard.log)"
    rc=1
  fi
else
  echo "== tier-1 fast suite (JAX_PLATFORMS=cpu, -m 'not slow') =="
  CS230_JOURNAL_DIR="$ART_DIR/journal" \
  CS230_METRICS_SNAPSHOT="$ART_DIR/metrics.prom" \
  CS230_EVENTS_SNAPSHOT="$ART_DIR/events_ring.jsonl" \
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || rc=$?
  # sharded-topology smoke: 2 shard subprocesses + 1 front end, reduced
  # client count, completion + routing asserted, NO latency gate — the
  # front/core split is exercised on every CI run, not just nightly
  echo "== sharded control-plane smoke (2 shards, 16 clients) =="
  if LOADTEST_SHARDS=2 LOADTEST_FRONTENDS=1 LOADTEST_CLIENTS=16 \
      LOADTEST_JOBS_PER_CLIENT=1 LOADTEST_EXECUTORS=1 \
      LOADTEST_OUT="$ART_DIR/loadtest_smoke.json" \
      JAX_PLATFORMS=cpu python benchmarks/loadtest.py --smoke \
      > "$ART_DIR/loadtest_smoke.log" 2>&1; then
    tail -n 1 "$ART_DIR/loadtest_smoke.log"
  else
    echo "sharded smoke FAILED (see $ART_DIR/loadtest_smoke.log)"
    tail -n 20 "$ART_DIR/loadtest_smoke.log"
    rc=1
  fi
fi

if [ "$rc" -eq 0 ]; then
  # green run: drop the artifacts (only red runs need the forensic record)
  rm -rf "$ART_DIR"
else
  echo "== suite failed (rc=$rc); trace/metric record kept in $ART_DIR =="
  ls -la "$ART_DIR" "$ART_DIR/journal" 2>/dev/null || true
fi
exit "$rc"
