#!/usr/bin/env bash
# CI gate, runnable locally or from .github/workflows/ci.yml:
#   1. compileall lint gate — every .py in the package, tests, and
#      benchmarks must byte-compile (catches syntax/indent rot with no
#      deps beyond the stdlib);
#   2. tier-1 fast suite — the ROADMAP.md verify command: pytest on the
#      virtual 8-device CPU mesh, slow (subprocess/chaos/minutes-long)
#      suites excluded.
# Wall time of the fast suite on the dev box is recorded in
# docs/STATUS.md; keep the two in sync when it moves.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint gate: python -m compileall =="
python -m compileall -q cs230_distributed_machine_learning_tpu tests benchmarks

echo "== tier-1 fast suite (JAX_PLATFORMS=cpu, -m 'not slow') =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider
