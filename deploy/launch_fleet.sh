#!/usr/bin/env bash
# One-command local fleet: coordinator server + N worker agents as real OS
# processes — the no-docker equivalent of deploy/compose.yaml (and of the
# reference's `docker-compose up`, minus Kafka/ZooKeeper/Redis).
#
#   deploy/launch_fleet.sh up [N_AGENTS=2] [PORT=5001]   # start + health-wait
#   deploy/launch_fleet.sh demo                          # run the titanic demo
#   deploy/launch_fleet.sh status                        # health plane snapshot
#   deploy/launch_fleet.sh down                          # stop everything
#
# State (pids/logs) lives in .fleet/ under the repo root.

set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
STATE="$REPO/.fleet"
PORT="${PORT:-5001}"
PY="${PYTHON:-python}"

up() {
  local n_agents="${1:-2}"
  mkdir -p "$STATE"
  echo "starting coordinator on :$PORT ..."
  (cd "$REPO" && PYTHONPATH="$REPO" nohup "$PY" -m \
      cs230_distributed_machine_learning_tpu.runtime.server \
      --host 127.0.0.1 --port "$PORT" --journal \
      > "$STATE/coordinator.log" 2>&1 & echo $! > "$STATE/coordinator.pid")
  for _ in $(seq 1 120); do
    if curl -fsS "$URL/health" > /dev/null 2>&1; then break; fi
    sleep 0.5
  done
  curl -fsS "$URL/health" > /dev/null || {
    echo "coordinator failed to come up; see $STATE/coordinator.log"; exit 1; }
  for i in $(seq 1 "$n_agents"); do
    echo "starting agent $i ..."
    (cd "$REPO" && PYTHONPATH="$REPO" nohup "$PY" -m \
        cs230_distributed_machine_learning_tpu.runtime.agent --url "$URL" \
        > "$STATE/agent$i.log" 2>&1 & echo $! > "$STATE/agent$i.pid")
  done
  # wait until every agent registered
  for _ in $(seq 1 120); do
    n_reg="$(curl -fsS "$URL/workers" | "$PY" -c \
        'import json,sys; print(len(json.load(sys.stdin)))' 2>/dev/null || echo 0)"
    [ "$n_reg" -ge "$n_agents" ] && break
    sleep 0.5
  done
  echo "fleet up: coordinator :$PORT + $n_reg agents (logs in $STATE/)"
}

demo() {
  (cd "$REPO" && PYTHONPATH="$REPO" "$PY" examples/demo_end_to_end.py --url "$URL")
}

# health-plane snapshot (docs/OBSERVABILITY.md "Fleet health plane"):
# firing alerts + the capacity signal an external autoscaler would read
status() {
  curl -fsS "$URL/alerts" | "$PY" -c '
import json, sys
b = json.load(sys.stdin)
firing = b.get("firing") or []
msg = "alerts: " + str(b["status"])
if firing:
    msg += " (%d firing: %s)" % (len(firing), firing)
print(msg)
'
  curl -fsS "$URL/autoscale" | "$PY" -c '
import json, sys
b = json.load(sys.stdin)
s = b["signals"]
print("autoscale: desired_workers=%s live_workers=%s backlog_s=%s pressure=%s"
      % (b["desired_workers"], b["live_workers"],
         s["backlog_seconds"], s["pressure"]))
'
}

down() {
  for f in "$STATE"/*.pid; do
    [ -e "$f" ] || continue
    kill "$(cat "$f")" 2>/dev/null || true
    rm -f "$f"
  done
  # belt-and-braces: pid files miss processes from a superseded `up` run
  pkill -f "cs230_distributed_machine_learning_tpu.runtime.server .*--port $PORT" 2>/dev/null || true
  pkill -f "cs230_distributed_machine_learning_tpu.runtime.agent --url $URL" 2>/dev/null || true
  echo "fleet stopped"
}

case "${1:-up}" in
  up)    PORT="${3:-$PORT}"; URL="http://127.0.0.1:${PORT}"; up "${2:-2}" ;;
  demo)   PORT="${2:-$PORT}"; URL="http://127.0.0.1:${PORT}"; demo ;;
  status) PORT="${2:-$PORT}"; URL="http://127.0.0.1:${PORT}"; status ;;
  down)   PORT="${2:-$PORT}"; URL="http://127.0.0.1:${PORT}"; down ;;
  *) echo "usage: $0 {up [n_agents] [port]|demo [port]|status [port]|down [port]}"; exit 2 ;;
esac
